"""Python half of the C API shim (``cpp/ltpu_capi.cpp``).

The reference exposes its whole framework through 58 exported C
functions (``include/LightGBM/c_api.h``, ``src/c_api.cpp``) that the
Python/R/SWIG bindings call.  This build inverts the stack — the
framework IS Python/JAX — so the stable non-Python entry point is a
C shared library embedding CPython and forwarding into this module.
Every function here takes/returns only C-friendly values (ints, str,
bytes, opaque object handles) so the C side stays a thin marshalling
layer.

Matrix buffers arrive as memoryviews over the caller's pointer
(``PyMemoryView_FromMemory``); they are copied into numpy immediately —
the C caller's buffer is never retained.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .basic import Booster, Dataset
from .config import Config

# C_API_DTYPE_* (c_api.h:20-23)
_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}
# C_API_PREDICT_* (c_api.h:25-28)
_PRED_NORMAL, _PRED_RAW, _PRED_LEAF, _PRED_CONTRIB = 0, 1, 2, 3


def _params(parameters: str) -> dict:
    return Config.str2dict(parameters or "")


def _mat(mv: memoryview, data_type: int, nrow: int, ncol: int,
         is_row_major: int) -> np.ndarray:
    dt = _DTYPES[data_type]
    arr = np.frombuffer(mv, dtype=dt, count=nrow * ncol)
    if is_row_major:
        return np.array(arr.reshape(nrow, ncol))
    return np.array(arr.reshape(ncol, nrow).T)


def _csr(indptr_mv, indptr_type, indices_mv, data_mv, data_type,
         nindptr: int, nelem: int, num_col: int):
    import scipy.sparse as sp
    ip_dt = _DTYPES[indptr_type]
    indptr = np.frombuffer(indptr_mv, dtype=ip_dt, count=nindptr)
    indices = np.frombuffer(indices_mv, dtype=np.int32, count=nelem)
    data = np.frombuffer(data_mv, dtype=_DTYPES[data_type], count=nelem)
    return sp.csr_matrix((data.copy(), indices.copy(), indptr.copy()),
                         shape=(nindptr - 1, num_col))


def _csc(col_ptr_mv, col_ptr_type, indices_mv, data_mv, data_type,
         ncol_ptr: int, nelem: int, num_row: int):
    import scipy.sparse as sp
    colptr = np.frombuffer(col_ptr_mv, dtype=_DTYPES[col_ptr_type],
                           count=ncol_ptr)
    indices = np.frombuffer(indices_mv, dtype=np.int32, count=nelem)
    data = np.frombuffer(data_mv, dtype=_DTYPES[data_type], count=nelem)
    return sp.csc_matrix((data.copy(), indices.copy(), colptr.copy()),
                         shape=(num_row, ncol_ptr - 1))


# ---- dataset -------------------------------------------------------------

def dataset_from_file(filename: str, parameters: str,
                      reference: Optional[Dataset]) -> Dataset:
    p = _params(parameters)
    d = Dataset(filename, params=p, reference=reference)
    d.construct()
    return d


def dataset_from_mat(mv: memoryview, data_type: int, nrow: int, ncol: int,
                     is_row_major: int, parameters: str,
                     reference: Optional[Dataset]) -> Dataset:
    X = _mat(mv, data_type, nrow, ncol, is_row_major)
    d = Dataset(X, params=_params(parameters), reference=reference)
    return d


def dataset_from_csr(indptr_mv, indptr_type, indices_mv, data_mv,
                     data_type, nindptr: int, nelem: int, num_col: int,
                     parameters: str, reference: Optional[Dataset]
                     ) -> Dataset:
    m = _csr(indptr_mv, indptr_type, indices_mv, data_mv, data_type,
             nindptr, nelem, num_col)
    return Dataset(m, params=_params(parameters), reference=reference)


def booster_predict_csr(b: Booster, indptr_mv, indptr_type, indices_mv,
                        data_mv, data_type, nindptr: int, nelem: int,
                        num_col: int, predict_type: int,
                        num_iteration: int, parameters: str) -> bytes:
    m = _csr(indptr_mv, indptr_type, indices_mv, data_mv, data_type,
             nindptr, nelem, num_col)
    return _predict(b, m, predict_type, num_iteration, parameters)


def dataset_from_csc(col_ptr_mv, col_ptr_type, indices_mv, data_mv,
                     data_type, ncol_ptr: int, nelem: int, num_row: int,
                     parameters: str, reference: Optional[Dataset]
                     ) -> Dataset:
    """LGBM_DatasetCreateFromCSC (c_api.h:169)."""
    m = _csc(col_ptr_mv, col_ptr_type, indices_mv, data_mv, data_type,
             ncol_ptr, nelem, num_row)
    return Dataset(m, params=_params(parameters), reference=reference)


def dataset_from_mats(mats, nrows, data_type: int, ncol: int,
                      is_row_major: int, parameters: str,
                      reference: Optional[Dataset]) -> Dataset:
    """LGBM_DatasetCreateFromMats (c_api.h:213): vertically stacked
    row-blocks become one matrix."""
    blocks = [_mat(mv, data_type, int(nr), ncol, is_row_major)
              for mv, nr in zip(mats, nrows)]
    return Dataset(np.vstack(blocks), params=_params(parameters),
                   reference=reference)


def dataset_create_by_reference(reference: Dataset,
                                num_total_row: int) -> Dataset:
    """LGBM_DatasetCreateByReference (c_api.h:81): empty dataset whose
    rows arrive via PushRows; bins align with the reference."""
    reference.construct()
    d = Dataset(None, params=dict(reference.params), reference=reference)
    d.begin_streaming(num_total_row, reference.num_feature())
    return d


def dataset_push_rows(d: Dataset, mv: memoryview, data_type: int,
                      nrow: int, ncol: int, start_row: int) -> None:
    """LGBM_DatasetPushRows (c_api.h:95)."""
    rows = _mat(mv, data_type, nrow, ncol, 1)
    d.push_rows(rows, start_row)


def dataset_push_rows_by_csr(d: Dataset, indptr_mv, indptr_type,
                             indices_mv, data_mv, data_type,
                             nindptr: int, nelem: int, num_col: int,
                             start_row: int) -> None:
    """LGBM_DatasetPushRowsByCSR (c_api.h:116)."""
    m = _csr(indptr_mv, indptr_type, indices_mv, data_mv, data_type,
             nindptr, nelem, num_col)
    d.push_rows(np.asarray(m.todense()), start_row)


def dataset_from_sampled_column(samples, sample_indices, ncol: int,
                                num_per_col, num_sample_row: int,
                                num_total_row: int,
                                parameters: str) -> Dataset:
    """LGBM_DatasetCreateFromSampledColumn (c_api.h:65): bin mappers are
    found from the per-column non-zero sample (zeros implied by the gap
    between len(sample) and num_sample_row — BinMapper.find_bin's
    sparse-sample contract), then rows stream in via PushRows."""
    from .io.binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper

    cfg = Config(_params(parameters))
    cf = cfg.categorical_feature
    if isinstance(cf, str):
        cat = {int(c) for c in cf.split(",")
               if c.strip().lstrip("-").isdigit()}
    else:
        cat = {int(c) for c in (cf or [])}
    mappers = []
    for j in range(ncol):
        n_j = int(num_per_col[j])
        vals = np.frombuffer(samples[j], dtype=np.float64, count=n_j)
        m = BinMapper()
        m.find_bin(np.array(vals), num_sample_row, cfg.max_bin,
                   min_data_in_bin=cfg.min_data_in_bin,
                   use_missing=cfg.use_missing,
                   zero_as_missing=cfg.zero_as_missing,
                   bin_type=BIN_CATEGORICAL if j in cat
                   else BIN_NUMERICAL)
        mappers.append(m)
    d = Dataset(None, params=_params(parameters))
    d._preset_mappers = mappers
    d.begin_streaming(num_total_row, ncol)
    return d


def dataset_get_subset(d: Dataset, indices_mv, num_indices: int,
                       parameters: str) -> Dataset:
    """LGBM_DatasetGetSubset (c_api.h:232)."""
    idx = np.frombuffer(indices_mv, dtype=np.int32, count=num_indices)
    sub = d.subset(np.array(idx), params=_params(parameters) or None)
    sub.construct()
    return sub


def dataset_set_feature_names(d: Dataset, names) -> None:
    d.set_feature_names(list(names))


def dataset_get_feature_names(d: Dataset) -> List[str]:
    names = d.get_feature_names()
    if not names:
        d.construct()
        names = d.get_feature_names()
    return list(names)


def dataset_update_param(d: Dataset, parameters: str) -> None:
    d.update_params(_params(parameters))


def dataset_set_field(d: Dataset, name: str, mv: memoryview,
                      num_element: int, data_type: int) -> None:
    arr = np.frombuffer(mv, dtype=_DTYPES[data_type], count=num_element)
    d.set_field(name, np.array(arr))


def dataset_get_field(d: Dataset, name: str):
    """(array, element count, dtype code).  The array is stashed on the
    Dataset so the C caller's pointer stays valid until DatasetFree
    (the reference returns pointers into dataset-owned memory too)."""
    v = d.get_field(name)
    if v is None:
        return None, 0, 0
    v = np.ascontiguousarray(v)
    if v.dtype == np.int32:
        code = 2
    elif name == "init_score":
        # the reference returns init_score as C_API_DTYPE_FLOAT64
        # (c_api.cpp DatasetGetField); label/weight stay f32
        v = np.ascontiguousarray(v, np.float64)
        code = 1
    else:
        v = np.ascontiguousarray(v, np.float32)
        code = 0
    d.__dict__.setdefault("_capi_field_bufs", {})[name] = v
    return v, int(v.size), code


def dataset_num_data(d: Dataset) -> int:
    return int(d.num_data())


def dataset_num_feature(d: Dataset) -> int:
    return int(d.num_feature())


def dataset_save_binary(d: Dataset, filename: str) -> None:
    d.save_binary(filename)


# ---- booster -------------------------------------------------------------

def booster_create(train: Dataset, parameters: str) -> Booster:
    return Booster(params=_params(parameters), train_set=train)


def booster_from_file(filename: str) -> Tuple[Booster, int]:
    b = Booster(model_file=filename)
    return b, int(b.current_iteration())


def booster_from_string(model_str: str) -> Tuple[Booster, int]:
    b = Booster(model_str=model_str)
    return b, int(b.current_iteration())


def booster_add_valid(b: Booster, d: Dataset, name: str) -> None:
    b.add_valid(d, name)


def booster_add_valid_auto(b: Booster, d: Dataset) -> None:
    """Name by THIS booster's valid-set count (a process-global counter
    would misnumber every booster after the first)."""
    n = len(b._gbdt.valid_sets) if b._gbdt is not None else 0
    b.add_valid(d, f"valid_{n}")


def booster_update(b: Booster) -> int:
    return 1 if b.update() else 0


def booster_update_custom(b: Booster, grad_mv: memoryview,
                          hess_mv: memoryview, n: int) -> int:
    # buffers are (num_class * num_data,) flat, class-major like the
    # reference's score arrays; reshape so the per-class loop in
    # train_one_iter sees (num_class, num_data)
    k = booster_num_classes(b)
    grad = np.array(np.frombuffer(grad_mv, dtype=np.float32, count=n))
    hess = np.array(np.frombuffer(hess_mv, dtype=np.float32, count=n))
    if k > 1:
        grad = grad.reshape(k, -1)
        hess = hess.reshape(k, -1)

    def fobj(preds, train_set):
        return grad, hess
    return 1 if b.update(fobj=fobj) else 0


def booster_rollback(b: Booster) -> None:
    b.rollback_one_iter()


def booster_num_data_for_custom(b: Booster) -> int:
    """Rows in the training set — the grad/hess length the C caller of
    LGBM_BoosterUpdateOneIterCustom must supply (× num classes)."""
    g = b._gbdt
    n = int(g.num_data) if g is not None else 0
    return n * booster_num_classes(b)


def booster_num_classes(b: Booster) -> int:
    g = b._gbdt
    if g is None:
        return 1
    return int(getattr(g, "num_class", 0) or
               getattr(g, "num_tree_per_iteration", 1))


def booster_current_iteration(b: Booster) -> int:
    return int(b.current_iteration())


def booster_num_feature(b: Booster) -> int:
    return len(b.feature_name())


def booster_eval(b: Booster, data_idx: int) -> bytes:
    """Metric values for data_idx (0 = train, i = i-th valid) as f64,
    evaluated on demand like the reference's GetEvalAt."""
    g = b._gbdt
    if g is None:
        return b""
    if data_idx == 0:
        rows = g._eval_one_set("training", g.train_score,
                               g.train_set.metadata)
        vals = [val for (_n, val, _hb) in _norm_rows(rows)]
    else:
        vals = []
        names_seen: List[str] = []
        for (dname, _mname, val, _hb) in b.eval_valid():
            if dname not in names_seen:
                names_seen.append(dname)
            if len(names_seen) == data_idx:
                vals.append(val)
    return np.asarray(vals, np.float64).tobytes()


def _norm_rows(rows) -> List[Tuple[str, float, bool]]:
    """_eval_one_set rows are (metric_name, value, higher_better)."""
    out = []
    for r in rows:
        if len(r) == 4:
            out.append((r[1], r[2], r[3]))
        else:
            out.append((r[0], r[1], r[2]))
    return out


def _inner_scores(b: Booster, data_idx: int) -> np.ndarray:
    """(num_class, rows) inner scores for data_idx (0 = train, i = i-th
    valid set in add order), transformed like the reference's
    GetPredictAt (ConvertOutput applied — probabilities for binary/
    multiclass, raw for regression)."""
    g = b._gbdt
    if g is None:
        return np.zeros((1, 0), np.float64)
    if data_idx < 0 or data_idx > len(g.valid_sets):
        raise ValueError(f"data_idx {data_idx} out of range "
                         f"(0..{len(g.valid_sets)})")
    raw = np.asarray(g.train_score if data_idx == 0
                     else g.valid_sets[data_idx - 1].score, np.float64)
    if g.objective is None:
        return raw
    out = g.objective.convert_output(raw.T if raw.shape[0] > 1
                                     else raw[0])
    out = np.asarray(out, np.float64)
    return out.T if out.ndim > 1 else out[None, :]


def booster_num_predict(b: Booster, data_idx: int) -> int:
    return int(_inner_scores(b, data_idx).size)


def booster_inner_predict(b: Booster, data_idx: int) -> bytes:
    return _inner_scores(b, data_idx).reshape(-1).tobytes()


def booster_eval_names(b: Booster) -> List[str]:
    """One name per value that booster_eval emits — rank metrics expand
    to one entry per eval_at position (ndcg@1..), matching the
    reference's GetEvalNames whose count sizes the caller's out_results
    buffer (``src/c_api.cpp`` GetEvalNames; metric ``GetName()`` returns
    the expanded vector).  A config-name list here would undercount and
    let LGBM_BoosterGetEval overrun a reference-contract caller's
    allocation."""
    g = b._gbdt
    if g is None:
        return list(getattr(b, "_metric_names", []) or [])
    names: List[str] = []
    for m in g.metrics:
        if hasattr(m, "eval_all") and hasattr(m, "eval_at"):
            names.extend(f"{m.name}@{k}" for k in m.eval_at)
        else:
            names.append(m.name)
    return names


def booster_feature_names(b: Booster) -> List[str]:
    return list(b.feature_name())


def booster_eval_counts(b: Booster) -> int:
    """LGBM_BoosterGetEvalCounts (c_api.h:495)."""
    return len(booster_eval_names(b))


def booster_merge(b: Booster, other: Booster) -> None:
    b.merge(other)


def booster_shuffle_models(b: Booster, start_iter: int,
                           end_iter: int) -> None:
    b.shuffle_models(start_iter, end_iter)


def booster_reset_training_data(b: Booster, train: Dataset) -> None:
    b.reset_training_data(train)


def booster_reset_parameter(b: Booster, parameters: str) -> None:
    b.reset_parameter(_params(parameters))


def booster_refit(b: Booster, leaf_preds_mv, nrow: int,
                  ncol: int) -> None:
    """LGBM_BoosterRefit (c_api.h:446): int32 (nrow, ncol) leaf preds,
    gradients from the training set."""
    lp = np.frombuffer(leaf_preds_mv, dtype=np.int32,
                       count=nrow * ncol).reshape(nrow, ncol)
    b._gbdt.refit_leaf_preds(np.array(lp))


def booster_num_model_per_iteration(b: Booster) -> int:
    g = b._gbdt
    return int(getattr(g, "num_tree_per_iteration", 1)) if g else 1


def booster_number_of_total_model(b: Booster) -> int:
    g = b._gbdt
    return len(g.models) if g else 0


def booster_get_leaf_value(b: Booster, tree_idx: int,
                           leaf_idx: int) -> float:
    tree = b._gbdt.models[tree_idx]
    if not (0 <= leaf_idx < tree.num_leaves):
        raise IndexError(f"leaf {leaf_idx} out of range "
                         f"(tree has {tree.num_leaves})")
    return float(tree.leaf_value[leaf_idx])


def booster_set_leaf_value(b: Booster, tree_idx: int, leaf_idx: int,
                           val: float) -> None:
    tree = b._gbdt.models[tree_idx]
    if not (0 <= leaf_idx < tree.num_leaves):
        raise IndexError(f"leaf {leaf_idx} out of range "
                         f"(tree has {tree.num_leaves})")
    tree.leaf_value[leaf_idx] = float(val)
    # in-place leaf mutation: the flattened-predictor tables are stale
    b._gbdt._invalidate_predictor()


def booster_feature_importance(b: Booster, num_iteration: int,
                               importance_type: int) -> bytes:
    """LGBM_BoosterFeatureImportance (c_api.h:792): f64 array, 0=split
    1=gain."""
    kind = "gain" if importance_type == 1 else "split"
    imp = b.feature_importance(
        importance_type=kind,
        iteration=num_iteration if num_iteration > 0 else None)
    return np.asarray(imp, np.float64).tobytes()


def booster_calc_num_predict(b: Booster, num_row: int, predict_type: int,
                             num_iteration: int) -> int:
    """LGBM_BoosterCalcNumPredict (c_api.h:597)."""
    g = b._gbdt
    k = booster_num_classes(b)
    n_iters = len(g.models) // max(g.num_tree_per_iteration, 1)
    if num_iteration > 0:
        n_iters = min(n_iters, num_iteration)
    if predict_type == _PRED_LEAF:
        return num_row * n_iters * max(g.num_tree_per_iteration, 1)
    if predict_type == _PRED_CONTRIB:
        return num_row * k * (booster_num_feature(b) + 1)
    return num_row * k


def booster_dump_model(b: Booster, start_iteration: int,
                       num_iteration: int) -> str:
    """LGBM_BoosterDumpModel (c_api.h:751): JSON text."""
    import json
    return json.dumps(b.dump_model(
        num_iteration=num_iteration if num_iteration > 0 else None,
        start_iteration=max(start_iteration, 0)))


def booster_predict_for_file(b: Booster, data_filename: str,
                             data_has_header: int, predict_type: int,
                             num_iteration: int, parameters: str,
                             result_filename: str) -> None:
    """LGBM_BoosterPredictForFile (c_api.h:577): parse, predict, write
    one line per row, values tab-joined (``Predictor::Predict``,
    ``src/application/predictor.hpp:130``)."""
    from .io.parser import parse_file
    X, _, _ = parse_file(data_filename, header=bool(data_has_header))
    raw = _predict(b, X, predict_type, num_iteration, parameters)
    out = np.frombuffer(raw, np.float64).reshape(X.shape[0], -1)
    with open(result_filename, "w") as f:
        for row in out:
            f.write("\t".join(f"{v:g}" for v in row) + "\n")


def booster_predict_csc(b: Booster, col_ptr_mv, col_ptr_type, indices_mv,
                        data_mv, data_type, ncol_ptr: int, nelem: int,
                        num_row: int, predict_type: int,
                        num_iteration: int, parameters: str) -> bytes:
    """LGBM_BoosterPredictForCSC (c_api.h:666)."""
    m = _csc(col_ptr_mv, col_ptr_type, indices_mv, data_mv, data_type,
             ncol_ptr, nelem, num_row)
    return _predict(b, m, predict_type, num_iteration, parameters)


def network_init(machines: str, local_listen_port: int,
                 listen_time_out: int, num_machines: int) -> None:
    """LGBM_NetworkInit (c_api.h:805): multi-process initialization.

    The TPU-native transport is ``jax.distributed`` + a global device
    mesh, not a socket mesh — ``parallel.distributed.init_from_machines``
    maps the reference's machine-list contract onto it.  A failure
    RAISES (C caller gets -1): silently degrading to single-node, as a
    no-op here once did, trains at the wrong scale (round-2 verdict)."""
    if num_machines <= 1:
        return
    from .parallel.distributed import init_from_machines
    init_from_machines(machines, local_listen_port, listen_time_out,
                       num_machines)


def network_free() -> None:
    from .parallel.distributed import shutdown
    shutdown()


def booster_save_model(b: Booster, start_iteration: int,
                       num_iteration: int, filename: str) -> None:
    b.save_model(filename,
                 num_iteration=num_iteration if num_iteration > 0 else None,
                 start_iteration=max(start_iteration, 0))


def booster_model_to_string(b: Booster, start_iteration: int,
                            num_iteration: int) -> str:
    return b.model_to_string(
        num_iteration=num_iteration if num_iteration > 0 else None,
        start_iteration=max(start_iteration, 0))


def _predict(b: Booster, data, predict_type: int, num_iteration: int,
             parameters: str) -> bytes:
    """Shared predict path for the mat/CSR entry points.

    ``num_iteration <= 0`` means the full ensemble (reference C-API
    semantics; ``Booster.predict`` treats an explicit 0/-1 the same
    way, only ``None`` falls back to best_iteration)."""
    kw = {}
    # str2dict values are raw strings; coerce through the registry so
    # "pred_early_stop=false" disables rather than truthy-enables.
    # predict_engine / predict_chunk_rows ride the same path: per-call
    # kwargs, never written to the shared booster config (concurrent
    # predicts on one booster stay safe)
    coerced = Config(_params(parameters)) if parameters else None
    for k in ("pred_early_stop", "pred_early_stop_freq",
              "pred_early_stop_margin", "predict_engine",
              "predict_chunk_rows"):
        if coerced is not None and k in coerced._user_set:
            kw[k] = getattr(coerced, k)
    out = b.predict(data, num_iteration=num_iteration,
                    raw_score=predict_type == _PRED_RAW,
                    pred_leaf=predict_type == _PRED_LEAF,
                    pred_contrib=predict_type == _PRED_CONTRIB, **kw)
    return np.asarray(out, np.float64).reshape(-1).tobytes()


def booster_predict_mat(b: Booster, mv: memoryview, data_type: int,
                        nrow: int, ncol: int, is_row_major: int,
                        predict_type: int, num_iteration: int,
                        parameters: str) -> bytes:
    X = _mat(mv, data_type, nrow, ncol, is_row_major)
    return _predict(b, X, predict_type, num_iteration, parameters)
