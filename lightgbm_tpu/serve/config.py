"""Typed serving-layer configuration.

The canonical parameter definitions (names, defaults, aliases, docs)
live in the single-source registry — ``lightgbm_tpu/config.py``, group
``serve`` — so ``docs/Parameters.md`` and CLI alias resolution cover
them like every other knob.  This dataclass is the resolved subset the
serve package passes around; build it with :meth:`ServeConfig.from_params`
from a raw params dict, a resolved :class:`~lightgbm_tpu.config.Config`,
or nothing (defaults).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Union


@dataclasses.dataclass
class ServeConfig:
    host: str = "127.0.0.1"
    port: int = 9595
    # coalescer: batches close at max_batch_rows or when the oldest
    # pending request has waited batch_wait_ms, whichever first.
    # max_batch_rows doubles as the engine row-chunk for serving, so
    # the servable bucket set is {512, 1024, ..., max_batch_rows}
    max_batch_rows: int = 1024
    batch_wait_ms: float = 2.0
    # admission bounds (rows is the real resource — device batch slots)
    queue_rows: int = 16384
    queue_requests: int = 1024
    # default per-request deadline; 0 disables
    timeout_ms: float = 2000.0
    workers: int = 1
    # pre-compile every bucket kernel at publish time, before the
    # version becomes visible (the zero-steady-state-compile contract)
    warmup: bool = True
    # single-row fast path: predict batches at most this many rows
    # with a shallow queue dispatch on tiny per-fingerprint buckets
    # (bit-identical outputs, lower p50); 0 disables the lane
    fastpath_max_rows: int = 8
    fastpath_max_queue: int = 2
    # engine compile-cache LRU capacity (must cover the layouts x
    # buckets being served; the serve path bypasses GBDT, so the
    # Server applies this itself at construction)
    predict_cache_slots: int = 16
    telemetry_file: str = ""
    # HTTP front hardening: reject request bodies beyond this many
    # bytes with a structured 413 before reading them
    max_body_bytes: int = 33554432
    # graceful drain (SIGTERM / supervisor restart): how long to wait
    # for admitted requests to complete before hard-stopping
    drain_grace_s: float = 10.0
    # when set, the HTTP front writes its bound port here once
    # listening (ephemeral-port discovery for the fleet supervisor)
    port_file: str = ""
    # expose POST/GET /faults (the fault-injection harness's remote
    # driving surface, utils/faults.py) — chaos tests only
    debug_faults: bool = False
    # GET /metrics (Prometheus text): live counters/gauges/bounded
    # histograms fed by the same call sites as the telemetry records
    metrics: bool = True
    # comma-separated latency-histogram bucket bounds in ms; () = the
    # built-in log-spaced ladder (obs/metrics.py)
    metrics_latency_buckets: tuple = ()

    @classmethod
    def from_params(cls, params: Union[None, Dict[str, Any], Any] = None
                    ) -> "ServeConfig":
        from ..config import Config
        if params is None:
            cfg = Config()
        elif isinstance(params, Config):
            cfg = params
        else:
            cfg = Config(dict(params))
        return cls(
            host=str(cfg.serve_host),
            port=int(cfg.serve_port),
            max_batch_rows=int(cfg.serve_max_batch_rows),
            batch_wait_ms=float(cfg.serve_batch_wait_ms),
            queue_rows=int(cfg.serve_queue_rows),
            queue_requests=int(cfg.serve_queue_requests),
            timeout_ms=float(cfg.serve_timeout_ms),
            workers=int(cfg.serve_workers),
            warmup=bool(cfg.serve_warmup),
            fastpath_max_rows=int(cfg.serve_fastpath_max_rows),
            fastpath_max_queue=int(cfg.serve_fastpath_max_queue),
            predict_cache_slots=int(cfg.predict_cache_slots),
            telemetry_file=str(cfg.telemetry_file or ""),
            max_body_bytes=int(cfg.serve_max_body_bytes),
            drain_grace_s=float(cfg.serve_drain_grace_s),
            port_file=str(cfg.serve_port_file or ""),
            debug_faults=bool(cfg.serve_debug_faults),
            metrics=bool(cfg.serve_metrics),
            metrics_latency_buckets=tuple(
                float(v) for v in
                str(cfg.serve_metrics_latency_buckets or "").split(",")
                if v.strip()))

    def validate(self) -> None:
        if self.max_batch_rows <= 0:
            raise ValueError("serve_max_batch_rows must be > 0")
        if self.queue_rows < self.max_batch_rows:
            raise ValueError("serve_queue_rows must be >= "
                             "serve_max_batch_rows")
        if self.workers < 1:
            raise ValueError("serve_workers must be >= 1")
        if self.batch_wait_ms < 0 or self.timeout_ms < 0:
            raise ValueError("serve wait/timeout must be >= 0")
        if self.max_body_bytes <= 0:
            raise ValueError("serve_max_body_bytes must be > 0")
        if self.fastpath_max_rows < 0 or self.fastpath_max_queue < 0:
            raise ValueError("serve_fastpath_max_rows/max_queue must "
                             "be >= 0")
        if self.drain_grace_s < 0:
            raise ValueError("serve_drain_grace_s must be >= 0")
        if self.metrics_latency_buckets and (
                any(b <= 0 for b in self.metrics_latency_buckets) or
                list(self.metrics_latency_buckets) !=
                sorted(self.metrics_latency_buckets)):
            raise ValueError("serve_metrics_latency_buckets must be "
                             "ascending positive bounds (ms)")


@dataclasses.dataclass
class RouterConfig:
    """Resolved knobs of the routing front (``serve/router.py``): the
    shared-nothing HTTP router in front of one or more replica fleets.
    Canonical definitions live in the ``route`` group of the
    ``lightgbm_tpu/config.py`` registry."""

    host: str = "127.0.0.1"
    port: int = 9700
    port_file: str = ""
    # balancer: /healthz scrape cadence + timeout per backend
    probe_interval_s: float = 0.25
    probe_timeout_s: float = 2.0
    # per-request total budget; retries/hedges fit INSIDE it
    timeout_ms: float = 10000.0
    # bounded retries on connect failure / 5xx (attempts beyond the
    # first; the hedge does not count against this)
    max_retries: int = 2
    # retry backoff: attempt n waits base * 2^(n-1) ms (capped), plus
    # deterministic jitter seeded by (seed, request id, attempt) —
    # clamped to the request's REMAINING budget
    backoff_base_ms: float = 25.0
    backoff_max_ms: float = 1000.0
    backoff_jitter: float = 0.5
    # tail-latency hedge: a second attempt to a DIFFERENT backend once
    # the first has been silent this long; first answer wins, the
    # loser's connection is torn down.  0 disables.
    hedge_ms: float = 75.0
    # per-backend circuit breaker feeding the balancer
    breaker_failures: int = 3
    breaker_cooldown_s: float = 5.0
    # per-model admission budgets (defaults; override per model via
    # Router.add_model): token-bucket rows/s (0 = unlimited), burst
    # capacity in rows, and an in-flight request cap (0 = unlimited).
    # Priority > 0 requests may overdraw one extra burst/cap before
    # shedding — cheap traffic sheds first.
    rows_per_s: float = 0.0
    burst_rows: int = 8192
    max_inflight: int = 256
    # admission weight of one explain row against the shared token
    # bucket (TreeSHAP costs O(depth^2) per leaf vs predict's O(depth))
    explain_cost: float = 4.0
    max_body_bytes: int = 33554432
    metrics: bool = True
    seed: int = 0
    # static backend table for the CLI (task=route):
    # "url" / "name=url+url" entries, comma separated
    backends: str = ""

    @classmethod
    def from_params(cls, params: Union[None, Dict[str, Any], Any] = None
                    ) -> "RouterConfig":
        from ..config import Config
        if params is None:
            cfg = Config()
        elif isinstance(params, Config):
            cfg = params
        else:
            cfg = Config(dict(params))
        return cls(
            host=str(cfg.route_host),
            port=int(cfg.route_port),
            port_file=str(cfg.route_port_file or ""),
            probe_interval_s=float(cfg.route_probe_interval_s),
            probe_timeout_s=float(cfg.route_probe_timeout_s),
            timeout_ms=float(cfg.route_timeout_ms),
            max_retries=int(cfg.route_max_retries),
            backoff_base_ms=float(cfg.route_backoff_base_ms),
            backoff_max_ms=float(cfg.route_backoff_max_ms),
            backoff_jitter=float(cfg.route_backoff_jitter),
            hedge_ms=float(cfg.route_hedge_ms),
            breaker_failures=int(cfg.route_breaker_failures),
            breaker_cooldown_s=float(cfg.route_breaker_cooldown_s),
            rows_per_s=float(cfg.route_rows_per_s),
            burst_rows=int(cfg.route_burst_rows),
            max_inflight=int(cfg.route_max_inflight),
            explain_cost=float(cfg.route_explain_cost),
            max_body_bytes=int(cfg.serve_max_body_bytes),
            metrics=bool(cfg.serve_metrics),
            seed=int(cfg.seed) if cfg.seed is not None else 0,
            backends=str(cfg.route_backends or ""))

    def validate(self) -> None:
        if self.probe_interval_s <= 0 or self.probe_timeout_s <= 0:
            raise ValueError("route probe interval/timeout must be > 0")
        if self.timeout_ms <= 0:
            raise ValueError("route_timeout_ms must be > 0")
        if self.max_retries < 0:
            raise ValueError("route_max_retries must be >= 0")
        if self.backoff_base_ms < 0 or \
                self.backoff_max_ms < self.backoff_base_ms:
            raise ValueError("route backoff must satisfy 0 <= base "
                             "<= max")
        if not 0 <= self.backoff_jitter <= 1:
            raise ValueError("route_backoff_jitter must be in [0, 1]")
        if self.hedge_ms < 0:
            raise ValueError("route_hedge_ms must be >= 0")
        if self.breaker_failures < 1 or self.breaker_cooldown_s < 0:
            raise ValueError("route breaker thresholds out of range")
        if self.rows_per_s < 0 or self.burst_rows < 1 or \
                self.max_inflight < 0:
            raise ValueError("route admission budget out of range")
        if self.explain_cost < 1:
            raise ValueError("route_explain_cost must be >= 1")


@dataclasses.dataclass
class FleetConfig:
    """Resolved knobs of the resilience layer: the replica supervisor
    (``serve/fleet.py``), the checkpoint watcher and the rollback
    controller (``serve/watcher.py``).  Canonical definitions live in
    the ``fleet`` group of the ``lightgbm_tpu/config.py`` registry."""

    replicas: int = 2
    # supervisor health probing
    probe_interval_s: float = 0.5
    probe_timeout_s: float = 2.0
    fail_threshold: int = 3
    # restart policy: exponential backoff with deterministic jitter
    backoff_base_s: float = 0.5
    backoff_max_s: float = 30.0
    backoff_jitter: float = 0.2
    # circuit breaker: after this many consecutive failed restarts the
    # replica leaves the rotation; cooldown 0 keeps it out for good
    circuit_failures: int = 5
    circuit_cooldown_s: float = 60.0
    seed: int = 0
    # checkpoint watcher
    watch_poll_s: float = 2.0
    # named tenant the watcher (and task=sweep) publishes under;
    # "default" keeps the unnamed /predict-/swap routes working
    tenant: str = "default"
    canary_file: str = ""
    canary_min_auc: float = 0.0
    canary_tolerance: float = 1e-6
    # telemetry-driven rollback
    rollback_window_s: float = 10.0
    rollback_min_requests: int = 50
    rollback_error_rate: float = 0.05
    rollback_p99_factor: float = 3.0
    rollback_p99_floor_ms: float = 5.0
    rollback_holddown_s: float = 60.0

    @classmethod
    def from_params(cls, params: Union[None, Dict[str, Any], Any] = None
                    ) -> "FleetConfig":
        from ..config import Config
        if params is None:
            cfg = Config()
        elif isinstance(params, Config):
            cfg = params
        else:
            cfg = Config(dict(params))
        return cls(
            replicas=int(cfg.fleet_replicas),
            probe_interval_s=float(cfg.fleet_probe_interval_s),
            probe_timeout_s=float(cfg.fleet_probe_timeout_s),
            fail_threshold=int(cfg.fleet_fail_threshold),
            backoff_base_s=float(cfg.fleet_backoff_base_s),
            backoff_max_s=float(cfg.fleet_backoff_max_s),
            backoff_jitter=float(cfg.fleet_backoff_jitter),
            circuit_failures=int(cfg.fleet_circuit_failures),
            circuit_cooldown_s=float(cfg.fleet_circuit_cooldown_s),
            seed=int(cfg.seed) if cfg.seed is not None else 0,
            watch_poll_s=float(cfg.watch_poll_s),
            tenant=str(cfg.watch_tenant or "default"),
            canary_file=str(cfg.canary_file or ""),
            canary_min_auc=float(cfg.canary_min_auc),
            canary_tolerance=float(cfg.canary_tolerance),
            rollback_window_s=float(cfg.rollback_window_s),
            rollback_min_requests=int(cfg.rollback_min_requests),
            rollback_error_rate=float(cfg.rollback_error_rate),
            rollback_p99_factor=float(cfg.rollback_p99_factor),
            rollback_p99_floor_ms=float(cfg.rollback_p99_floor_ms),
            rollback_holddown_s=float(cfg.rollback_holddown_s))

    def validate(self) -> None:
        if self.replicas < 1:
            raise ValueError("fleet_replicas must be >= 1")
        if self.probe_interval_s <= 0 or self.probe_timeout_s <= 0:
            raise ValueError("fleet probe interval/timeout must be > 0")
        if self.fail_threshold < 1 or self.circuit_failures < 1:
            raise ValueError("fleet failure thresholds must be >= 1")
        if self.backoff_base_s < 0 or self.backoff_max_s < \
                self.backoff_base_s:
            raise ValueError("fleet backoff must satisfy 0 <= base "
                             "<= max")
        if not 0 <= self.backoff_jitter <= 1:
            raise ValueError("fleet_backoff_jitter must be in [0, 1]")
        if self.rollback_min_requests < 1:
            raise ValueError("rollback_min_requests must be >= 1")
        if self.rollback_error_rate < 0 or self.rollback_p99_factor <= 0:
            raise ValueError("rollback thresholds must be positive")


@dataclasses.dataclass
class SloConfig:
    """Resolved knobs of the SLO engine (``obs/slo.py``): declarative
    objectives + multi-window multi-burn-rate evaluation.  Canonical
    definitions live in the ``slo`` group of the
    ``lightgbm_tpu/config.py`` registry."""

    enable: bool = False
    # evaluation cadence; windows are trailing from each tick
    interval_s: float = 5.0
    window_fast_s: float = 60.0
    window_mid_s: float = 300.0
    window_slow_s: float = 1800.0
    # burn-rate alert thresholds: fast is page-grade (must exceed on
    # BOTH fast and mid windows), slow is ticket-grade (slow window
    # alone).  14.4 is the classic "30-day budget in 2 days" pace.
    fast_burn: float = 14.4
    slow_burn: float = 3.0
    # wall-clock error-budget accounting period
    budget_window_s: float = 86400.0
    # budget persistence across replica restarts ("" = in-memory only)
    state_file: str = ""
    # objective targets (router_objectives standard set)
    availability_target: float = 0.999
    latency_p99_ms: float = 250.0
    latency_target: float = 0.99
    queue_saturation: float = 0.8
    queue_target: float = 0.99
    shed_target: float = 0.99

    @classmethod
    def from_params(cls, params: Union[None, Dict[str, Any], Any] = None
                    ) -> "SloConfig":
        from ..config import Config
        if params is None:
            cfg = Config()
        elif isinstance(params, Config):
            cfg = params
        else:
            cfg = Config(dict(params))
        return cls(
            enable=bool(cfg.slo_enable),
            interval_s=float(cfg.slo_interval_s),
            window_fast_s=float(cfg.slo_window_fast_s),
            window_mid_s=float(cfg.slo_window_mid_s),
            window_slow_s=float(cfg.slo_window_slow_s),
            fast_burn=float(cfg.slo_fast_burn),
            slow_burn=float(cfg.slo_slow_burn),
            budget_window_s=float(cfg.slo_budget_window_s),
            state_file=str(cfg.slo_state_file or ""),
            availability_target=float(cfg.slo_availability_target),
            latency_p99_ms=float(cfg.slo_latency_p99_ms),
            latency_target=float(cfg.slo_latency_target),
            queue_saturation=float(cfg.slo_queue_saturation),
            queue_target=float(cfg.slo_queue_target),
            shed_target=float(cfg.slo_shed_target))

    def validate(self) -> None:
        if self.interval_s <= 0:
            raise ValueError("slo_interval_s must be > 0")
        if not (0 < self.window_fast_s <= self.window_mid_s
                <= self.window_slow_s):
            raise ValueError("slo windows must satisfy 0 < fast <= "
                             "mid <= slow")
        if self.budget_window_s < self.window_slow_s:
            raise ValueError("slo_budget_window_s must be >= "
                             "slo_window_slow_s")
        if self.fast_burn <= 0 or self.slow_burn <= 0:
            raise ValueError("slo burn thresholds must be > 0")
        for name, v in (("slo_availability_target",
                         self.availability_target),
                        ("slo_latency_target", self.latency_target),
                        ("slo_queue_target", self.queue_target),
                        ("slo_shed_target", self.shed_target)):
            if not 0.0 < v < 1.0:
                raise ValueError(f"{name} must be in (0, 1)")
        if self.latency_p99_ms <= 0:
            raise ValueError("slo_latency_p99_ms must be > 0")
        if not 0.0 < self.queue_saturation <= 1.0:
            raise ValueError("slo_queue_saturation must be in (0, 1]")


@dataclasses.dataclass
class AutoscaleConfig:
    """Resolved knobs of the closed-loop autoscaler
    (``serve/autoscaler.py``).  Canonical definitions live in the
    ``autoscale`` group of the ``lightgbm_tpu/config.py`` registry."""

    enable: bool = False
    # compute + emit decisions without touching the fleet/buckets
    dry_run: bool = False
    interval_s: float = 2.0
    # replica bounds the controller may never cross
    min_replicas: int = 1
    max_replicas: int = 4
    # grow triggers: page-grade burn (both fast windows) OR in-flight
    # occupancy at/above this fraction of routing capacity
    grow_burn: float = 2.0
    grow_queue: float = 0.8
    # drain hysteresis: occupancy below drain_util AND burn cleared,
    # sustained for drain_idle_s, before one replica drains
    drain_idle_s: float = 60.0
    drain_util: float = 0.2
    # per-direction cooldowns (anti-flap)
    cooldown_s: float = 30.0
    drain_cooldown_s: float = 60.0
    # admission retune: per-model token-bucket rate while shedding
    shed_rows_per_s: float = 256.0
    # retune admission down once budget remaining falls below this
    budget_floor: float = 0.25

    @classmethod
    def from_params(cls, params: Union[None, Dict[str, Any], Any] = None
                    ) -> "AutoscaleConfig":
        from ..config import Config
        if params is None:
            cfg = Config()
        elif isinstance(params, Config):
            cfg = params
        else:
            cfg = Config(dict(params))
        return cls(
            enable=bool(cfg.autoscale),
            dry_run=bool(cfg.autoscale_dry_run),
            interval_s=float(cfg.autoscale_interval_s),
            min_replicas=int(cfg.autoscale_min_replicas),
            max_replicas=int(cfg.autoscale_max_replicas),
            grow_burn=float(cfg.autoscale_grow_burn),
            grow_queue=float(cfg.autoscale_grow_queue),
            drain_idle_s=float(cfg.autoscale_drain_idle_s),
            drain_util=float(cfg.autoscale_drain_util),
            cooldown_s=float(cfg.autoscale_cooldown_s),
            drain_cooldown_s=float(cfg.autoscale_drain_cooldown_s),
            shed_rows_per_s=float(cfg.autoscale_shed_rows_per_s),
            budget_floor=float(cfg.autoscale_budget_floor))

    def validate(self) -> None:
        if self.interval_s <= 0:
            raise ValueError("autoscale_interval_s must be > 0")
        if self.min_replicas < 1 or \
                self.max_replicas < self.min_replicas:
            raise ValueError("autoscale replicas must satisfy 1 <= "
                             "min <= max")
        if self.grow_burn <= 0:
            raise ValueError("autoscale_grow_burn must be > 0")
        if not 0.0 < self.grow_queue <= 1.0:
            raise ValueError("autoscale_grow_queue must be in (0, 1]")
        if self.drain_idle_s < 0 or self.cooldown_s < 0 or \
                self.drain_cooldown_s < 0:
            raise ValueError("autoscale cooldowns must be >= 0")
        if not 0.0 <= self.drain_util < self.grow_queue:
            raise ValueError("autoscale_drain_util must be in "
                             "[0, autoscale_grow_queue)")
        if self.shed_rows_per_s <= 0:
            raise ValueError("autoscale_shed_rows_per_s must be > 0")
        if not 0.0 <= self.budget_floor < 1.0:
            raise ValueError("autoscale_budget_floor must be in [0, 1)")
