"""Typed serving-layer configuration.

The canonical parameter definitions (names, defaults, aliases, docs)
live in the single-source registry — ``lightgbm_tpu/config.py``, group
``serve`` — so ``docs/Parameters.md`` and CLI alias resolution cover
them like every other knob.  This dataclass is the resolved subset the
serve package passes around; build it with :meth:`ServeConfig.from_params`
from a raw params dict, a resolved :class:`~lightgbm_tpu.config.Config`,
or nothing (defaults).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Union


@dataclasses.dataclass
class ServeConfig:
    host: str = "127.0.0.1"
    port: int = 9595
    # coalescer: batches close at max_batch_rows or when the oldest
    # pending request has waited batch_wait_ms, whichever first.
    # max_batch_rows doubles as the engine row-chunk for serving, so
    # the servable bucket set is {512, 1024, ..., max_batch_rows}
    max_batch_rows: int = 1024
    batch_wait_ms: float = 2.0
    # admission bounds (rows is the real resource — device batch slots)
    queue_rows: int = 16384
    queue_requests: int = 1024
    # default per-request deadline; 0 disables
    timeout_ms: float = 2000.0
    workers: int = 1
    # pre-compile every bucket kernel at publish time, before the
    # version becomes visible (the zero-steady-state-compile contract)
    warmup: bool = True
    # engine compile-cache LRU capacity (must cover the layouts x
    # buckets being served; the serve path bypasses GBDT, so the
    # Server applies this itself at construction)
    predict_cache_slots: int = 16
    telemetry_file: str = ""

    @classmethod
    def from_params(cls, params: Union[None, Dict[str, Any], Any] = None
                    ) -> "ServeConfig":
        from ..config import Config
        if params is None:
            cfg = Config()
        elif isinstance(params, Config):
            cfg = params
        else:
            cfg = Config(dict(params))
        return cls(
            host=str(cfg.serve_host),
            port=int(cfg.serve_port),
            max_batch_rows=int(cfg.serve_max_batch_rows),
            batch_wait_ms=float(cfg.serve_batch_wait_ms),
            queue_rows=int(cfg.serve_queue_rows),
            queue_requests=int(cfg.serve_queue_requests),
            timeout_ms=float(cfg.serve_timeout_ms),
            workers=int(cfg.serve_workers),
            warmup=bool(cfg.serve_warmup),
            predict_cache_slots=int(cfg.predict_cache_slots),
            telemetry_file=str(cfg.telemetry_file or ""))

    def validate(self) -> None:
        if self.max_batch_rows <= 0:
            raise ValueError("serve_max_batch_rows must be > 0")
        if self.queue_rows < self.max_batch_rows:
            raise ValueError("serve_queue_rows must be >= "
                             "serve_max_batch_rows")
        if self.workers < 1:
            raise ValueError("serve_workers must be >= 1")
        if self.batch_wait_ms < 0 or self.timeout_ms < 0:
            raise ValueError("serve wait/timeout must be >= 0")
