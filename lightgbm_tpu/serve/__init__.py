"""Online serving subsystem: micro-batching over the jitted predictor.

The reference C++ stack stops at batch prediction (``task=predict``
reads a file, writes a file); this package is the request-level layer
that turns the flattened jitted inference engine (``ops/predict.py``)
into an online service, following the micro-batching / continuous-
serving playbook of accelerator inference stacks (PAPERS.md:
"Fine-Tuning and Serving Gemma on Cloud TPU"; "GPU-acceleration for
Large-scale Tree Boosting" for the low-latency inference focus):

- :mod:`.admission`  — bounded request queue, backpressure
  (reject-with-retry-after), priority load-shedding, deadline sweep.
- :mod:`.batcher`    — coalesces concurrent requests into exactly the
  power-of-two row buckets the engine already compiles for, so
  steady-state serving incurs ZERO new XLA compiles.
- :mod:`.registry`   — versioned models with atomic hot-swap: a new
  version is flattened and pre-warmed against the live bucket set
  BEFORE it becomes visible; in-flight requests complete against the
  version they were admitted under.
- :mod:`.server`     — the in-process front (``Server(booster)``) and
  the dispatcher loop feeding per-request ``serve`` telemetry records
  (``utils/telemetry.py``).
- :mod:`.http`       — stdlib threaded JSON endpoint
  (``python -m lightgbm_tpu task=serve input_model=...``).
- :mod:`.fleet`      — replica supervisor: health probing, restart
  with backoff + jitter, circuit breaker, desired-model
  reconciliation (``docs/Resilience.md``).
- :mod:`.watcher`    — checkpoint-root watcher (manifest verify +
  canary scoring before auto-publish) and the telemetry-driven
  rollback controller.
- :mod:`.router`     — the resilient routing front above the fleet:
  health/draining/fingerprint-aware balancing, bounded retries +
  tail-latency hedging, per-backend circuit breakers, per-model
  admission budgets, and the multi-model tenancy table
  (``POST /v1/<model>/predict``, ``docs/Routing.md``).
- :mod:`.autoscaler` — the closed-loop controller above all of it:
  consumes the SLO engine's burn rates (``obs/slo.py``) plus the live
  router gauges and grows/drains fleet replicas and retunes per-model
  admission budgets, every decision a traced ``autoscale`` telemetry
  record (``docs/Serving.md``).
"""
from .admission import (AdmissionQueue, QueueSaturated, Request,
                        RequestShed, RequestTimeout, ServeError,
                        ServerClosed, UnknownModel)
from .autoscaler import Autoscaler
from .config import (AutoscaleConfig, FleetConfig, RouterConfig,
                     ServeConfig, SloConfig)
from .fleet import FleetSupervisor, InprocReplica, ProcessReplica
from .registry import ModelRegistry, ModelVersion, model_fingerprint
from .router import Router, route_http
from .server import Server
from .watcher import (CanarySet, CheckpointWatcher, FleetTarget,
                      RegistryTarget)

__all__ = [
    "Server", "ServeConfig", "FleetConfig", "RouterConfig",
    "SloConfig", "AutoscaleConfig", "Autoscaler",
    "ModelRegistry", "ModelVersion", "model_fingerprint",
    "AdmissionQueue", "Request", "ServeError", "QueueSaturated",
    "RequestShed", "RequestTimeout", "ServerClosed", "UnknownModel",
    "FleetSupervisor", "InprocReplica", "ProcessReplica", "Router",
    "route_http", "CanarySet", "CheckpointWatcher", "FleetTarget",
    "RegistryTarget",
]
