"""Micro-batching coalescer: requests -> engine-bucket-aligned batches.

The scheduler's whole job is to make online traffic look like the
batch traffic the inference engine already compiled for: concurrent
requests are packed (FIFO, same model version) into one matrix whose
row count the engine pads to exactly the power-of-two buckets
``PredictEngine._buckets`` serves — so a warmed server takes ZERO new
XLA compiles in steady state, whatever mix of request sizes arrives.
The max-wait / max-batch policy is the classic latency/throughput
knob: a batch closes when it reaches ``max_batch_rows`` or when the
oldest admitted request has waited ``batch_wait_ms``.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from .admission import AdmissionQueue, Request
from .config import ServeConfig


class Batch:
    """One assembled dispatch unit (one version, one kind)."""

    __slots__ = ("requests", "X", "rows", "bucket_rows", "version",
                 "kind", "fastpath", "assemble_ms")

    def __init__(self, requests: List[Request], X: np.ndarray,
                 bucket_rows: int, assemble_ms: float,
                 fastpath: bool = False):
        self.requests = requests
        self.X = X
        self.rows = int(X.shape[0])
        self.bucket_rows = int(bucket_rows)   # engine-padded total
        self.version = requests[0].version
        self.kind = requests[0].kind
        self.fastpath = bool(fastpath)
        self.assemble_ms = assemble_ms

    @property
    def occupancy(self) -> float:
        """Real rows / padded device rows — the wasted-compute gauge."""
        return self.rows / max(self.bucket_rows, 1)


class MicroBatcher:
    """Drains the admission queue into :class:`Batch` objects."""

    def __init__(self, queue: AdmissionQueue, config: ServeConfig):
        self.queue = queue
        self.config = config

    def next_batch(self, stop: threading.Event
                   ) -> Tuple[Optional[Batch], List[Request]]:
        """Block for the next batch.  Returns ``(batch, timed_out)``;
        ``batch`` is None when the server is stopping and the queue
        has drained."""
        reqs, timed = self.queue.drain_batch(
            self.config.max_batch_rows,
            self.config.batch_wait_ms / 1e3, stop)
        if not reqs:
            return None, timed
        t0 = time.monotonic()
        for r in reqs:
            r.timings["queue_ms"] = round((t0 - r.t_admit) * 1e3, 3)
        if len(reqs) == 1:
            X = reqs[0].X
        else:
            X = np.concatenate([r.X for r in reqs], axis=0)
        ver = reqs[0].version
        fastpath = False
        if reqs[0].kind == "explain":
            # explanation lane: the ShapEngine has its own bucket
            # ladder (128-row floor, bytes-capped chunk)
            bucket = ver.padded_explain_rows(
                X.shape[0], self.config.max_batch_rows)
        else:
            # occupancy-routed single-row fast path: at low load a
            # tiny predict batch skips the 512-row minimum bucket and
            # runs the per-fingerprint scalar-sized program warmed at
            # publish — bit-identical outputs, much less padded work.
            # The queue-depth gate keeps the lane off under pressure,
            # where coalescing into big buckets wins throughput.
            fp_rows = self.config.fastpath_max_rows
            if (0 < X.shape[0] <= fp_rows and
                    self.queue.depth()[0] <=
                    self.config.fastpath_max_queue):
                fastpath = True
                bucket = 1 << max(int(X.shape[0]) - 1, 0).bit_length()
            else:
                bucket = ver.padded_rows(
                    X.shape[0], self.config.max_batch_rows)
        assemble_ms = round((time.monotonic() - t0) * 1e3, 3)
        for r in reqs:
            r.timings["assemble_ms"] = assemble_ms
        return Batch(reqs, X, bucket, assemble_ms,
                     fastpath=fastpath), timed
