"""Micro-batching coalescer: requests -> engine-bucket-aligned batches.

The scheduler's whole job is to make online traffic look like the
batch traffic the inference engine already compiled for: concurrent
requests are packed (FIFO, same model version) into one matrix whose
row count the engine pads to exactly the power-of-two buckets
``PredictEngine._buckets`` serves — so a warmed server takes ZERO new
XLA compiles in steady state, whatever mix of request sizes arrives.
The max-wait / max-batch policy is the classic latency/throughput
knob: a batch closes when it reaches ``max_batch_rows`` or when the
oldest admitted request has waited ``batch_wait_ms``.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from .admission import AdmissionQueue, Request
from .config import ServeConfig


class Batch:
    """One assembled dispatch unit."""

    __slots__ = ("requests", "X", "rows", "bucket_rows", "version",
                 "assemble_ms")

    def __init__(self, requests: List[Request], X: np.ndarray,
                 bucket_rows: int, assemble_ms: float):
        self.requests = requests
        self.X = X
        self.rows = int(X.shape[0])
        self.bucket_rows = int(bucket_rows)   # engine-padded total
        self.version = requests[0].version
        self.assemble_ms = assemble_ms

    @property
    def occupancy(self) -> float:
        """Real rows / padded device rows — the wasted-compute gauge."""
        return self.rows / max(self.bucket_rows, 1)


class MicroBatcher:
    """Drains the admission queue into :class:`Batch` objects."""

    def __init__(self, queue: AdmissionQueue, config: ServeConfig):
        self.queue = queue
        self.config = config

    def next_batch(self, stop: threading.Event
                   ) -> Tuple[Optional[Batch], List[Request]]:
        """Block for the next batch.  Returns ``(batch, timed_out)``;
        ``batch`` is None when the server is stopping and the queue
        has drained."""
        reqs, timed = self.queue.drain_batch(
            self.config.max_batch_rows,
            self.config.batch_wait_ms / 1e3, stop)
        if not reqs:
            return None, timed
        t0 = time.monotonic()
        for r in reqs:
            r.timings["queue_ms"] = round((t0 - r.t_admit) * 1e3, 3)
        if len(reqs) == 1:
            X = reqs[0].X
        else:
            X = np.concatenate([r.X for r in reqs], axis=0)
        bucket = reqs[0].version.padded_rows(
            X.shape[0], self.config.max_batch_rows)
        assemble_ms = round((time.monotonic() - t0) * 1e3, 3)
        for r in reqs:
            r.timings["assemble_ms"] = assemble_ms
        return Batch(reqs, X, bucket, assemble_ms), timed
