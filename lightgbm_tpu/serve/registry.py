"""Versioned model registry with atomic pre-warmed hot-swap.

Publishing a new model version is a three-step transaction:

1. **flatten** — the booster's forest is packed into the SoA device
   tables the engine scores from (``ops/predict.py flatten_forest``,
   via the booster's own cached ``_flat_forest``);
2. **pre-warm** — every kernel the live serve bucket set can hit
   (``PredictEngine.bucket_set``) is compiled by running a real
   predict per bucket, BEFORE the version becomes visible;
3. **swap** — one atomic pointer assignment makes the version the
   admission target.

Because requests pin their :class:`ModelVersion` at admission and the
old version object stays alive as long as any in-flight request
references it, a swap never drops or mixes responses: old-version
batches keep completing against the old tables while new admissions
score against the new ones.  Steady-state compile count stays flat
across swaps of same-layout models (the engine compile cache is keyed
by layout statics, not by version), and a layout-changing swap pays
its compiles inside ``publish()``, never on the request path.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.log import Log
from ..utils.telemetry import counters as _tele_counters
from ..utils.telemetry import counters_snapshot


def model_fingerprint(model_text: str) -> str:
    """Content-addressed model identity: sha256 of the reference-format
    model text, truncated.  Unlike the per-registry ``version`` integer
    (which restarts from 1 with each replica process), the fingerprint
    is stable across the whole fleet — it is how the watcher, the
    rollback controller and the load generator agree on WHICH model a
    response was scored by."""
    return hashlib.sha256(model_text.encode("utf-8")).hexdigest()[:12]


class ModelVersion:
    """One immutable published model: booster + flattened tables."""

    def __init__(self, version: int, booster, chunk_rows: int,
                 fastpath_rows: int = 0):
        self.version = int(version)
        self.booster = booster
        self.chunk_rows = int(chunk_rows)
        self.fastpath_rows = int(fastpath_rows)
        # the flattened tables ARE the version snapshot: flatten_forest
        # builds fresh arrays, so later mutations of the booster
        # (continue-training, refit, DART renorm) never reach scoring
        # through this version — requests admitted under it really do
        # complete against the model as published
        self.flat = booster._gbdt._flat_forest()
        # the explanation lane's SoA tables (ops/shap.py), pinned for
        # the same post-publish-mutation immunity as ``flat``
        self.shap = booster._gbdt._shap_forest()
        self._objective = booster._gbdt.objective
        self.average_output = bool(getattr(booster._gbdt,
                                           "average_output", False))
        self.n_trees = self.flat.n_trees
        self.k = self.flat.k
        self.num_features = self.flat.num_features
        self.requires_features = self.flat.requires_features
        # the model text is retained on the version: it serves
        # GET /model (the watcher's rollback baseline capture) and is
        # what the fingerprint — the fleet-wide identity — is taken of
        self.model_text: str = booster.model_to_string(num_iteration=-1)
        self.model_id: str = model_fingerprint(self.model_text)
        self.published_at = time.time()
        self.warmup_info: Optional[Dict[str, Any]] = None

    # -- scoring ---------------------------------------------------------
    def predict_raw_batch(self, X: np.ndarray) -> np.ndarray:
        """Raw scores for an assembled batch, straight from the PINNED
        flattened tables — same semantics as ``GBDT.predict_raw``
        (engine scoring, average_output normalization, (rows,) /
        (rows, k) shape) but immune to post-publish booster mutation.
        The serve path is engine-only: ``LTPU_PREDICT_ENGINE=0``
        (the offline oracle toggle) does not apply here."""
        from ..ops.predict import get_engine
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        out = get_engine().predict_raw(self.flat, X, self.n_trees,
                                       chunk_rows=self.chunk_rows)
        if self.average_output and self.n_trees:
            out = out / max(self.n_trees // self.k, 1)
        return out[0] if self.k == 1 else out.T

    def predict_raw_fast_batch(self, X: np.ndarray) -> np.ndarray:
        """The single-row fast path: same pinned tables, same kernels,
        dispatched on the tiny power-of-two bucket matching this batch
        instead of the 512-row serving floor — bit-identical outputs
        (pinned by ``tests/test_shap_engine.py``), a fraction of the
        padded device work at occupancy ~1."""
        from ..ops.predict import get_engine
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        out = get_engine().predict_raw_fast(self.flat, X, self.n_trees)
        if self.average_output and self.n_trees:
            out = out / max(self.n_trees // self.k, 1)
        return out[0] if self.k == 1 else out.T

    def explain_batch(self, X: np.ndarray) -> np.ndarray:
        """Per-row SHAP contributions for an assembled explain batch,
        straight from the pinned :class:`~..ops.shap.ShapForest`
        tables — ``Booster.predict(pred_contrib=True)`` layout
        ((rows, nf+1); multiclass (rows, k*(nf+1))), rows first so the
        dispatcher slices per request like predict."""
        from ..ops.shap import get_shap_engine
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        raw = get_shap_engine().predict_contrib(
            self.shap, X, self.n_trees, chunk_rows=self.chunk_rows)
        rows = X.shape[0]
        out = np.moveaxis(raw, 2, 0)       # (rows, k, nf+1)
        return out[:, 0, :] if self.k == 1 else \
            np.ascontiguousarray(out.reshape(rows, -1))

    def convert(self, raw: np.ndarray) -> np.ndarray:
        """Raw -> output space (sigmoid/softmax/exp per objective)."""
        obj = self._objective
        return obj.convert_output(raw) if obj is not None else raw

    def padded_rows(self, n: int, chunk_rows: Optional[int] = None
                    ) -> int:
        from ..ops.predict import get_engine
        return get_engine().padded_rows(self.flat, n,
                                        chunk_rows or self.chunk_rows)

    def padded_explain_rows(self, n: int,
                            chunk_rows: Optional[int] = None) -> int:
        from ..ops.shap import get_shap_engine
        return get_shap_engine().padded_rows(
            self.shap, n, chunk_rows or self.chunk_rows)

    # -- warmup ----------------------------------------------------------
    def warmup(self) -> Dict[str, Any]:
        """Compile every kernel the serve bucket sets can hit for this
        layout — the predict ladder, the explain ladder AND the
        fast-path tiny buckets — before the version becomes the
        admission target.  Returns ``{buckets, explain_buckets,
        fastpath_buckets, xla_compiles, warmup_s}`` so the caller can
        record what the swap cost off the request path.  Because
        fleet reconciliation republishes through this same method, a
        restarted replica rejoins with its explain and fast-path
        kernels already compiled — it never compiles on its first
        explain request."""
        from ..ops.predict import PredictEngine, get_engine
        from ..ops.shap import get_shap_engine
        from ..utils.telemetry import install_jax_hooks
        engine = get_engine()
        buckets = engine.bucket_set(self.flat, self.chunk_rows)
        explain_buckets = get_shap_engine().bucket_set(
            self.shap, self.chunk_rows)
        fast_buckets = PredictEngine.fast_bucket_set(
            self.fastpath_rows) if self.fastpath_rows > 0 else []
        # the compile counter only counts once the jax.monitoring
        # hooks exist; a recorder-less Server never installed them,
        # which made every warmup report 0 compiles (idempotent)
        install_jax_hooks()
        base = counters_snapshot()
        t0 = time.monotonic()
        for b in buckets:
            self.predict_raw_batch(np.zeros((b, self.num_features)))
        for b in fast_buckets:
            self.predict_raw_fast_batch(
                np.zeros((b, self.num_features)))
        for b in explain_buckets:
            self.explain_batch(np.zeros((b, self.num_features)))
        now = counters_snapshot()
        info = {
            "buckets": list(buckets),
            "explain_buckets": list(explain_buckets),
            "fastpath_buckets": list(fast_buckets),
            "xla_compiles": now.get("xla_compiles", 0.0) -
            base.get("xla_compiles", 0.0),
            "warmup_s": round(time.monotonic() - t0, 3),
        }
        self.warmup_info = info
        return info

    def meta(self) -> Dict[str, Any]:
        return {"version": self.version, "model_id": self.model_id,
                "n_trees": self.n_trees,
                "num_features": self.num_features,
                "published_at": round(self.published_at, 3),
                "warmup": self.warmup_info}


class ModelRegistry:
    """Holds the active :class:`ModelVersion`; swaps are serialized
    and atomic (one pointer assignment under the lock)."""

    def __init__(self, chunk_rows: int = 1024, warm: bool = True,
                 fastpath_rows: int = 0):
        self.chunk_rows = int(chunk_rows)
        self.warm = bool(warm)
        self.fastpath_rows = int(fastpath_rows)
        self._lock = threading.Lock()          # guards _active/_history
        self._publish_lock = threading.Lock()  # serializes publishes
        self._active: Optional[ModelVersion] = None
        self._next_version = 1
        self._history: List[Dict[str, Any]] = []

    # -- publish / swap --------------------------------------------------
    def publish(self, booster=None, model_file: Optional[str] = None,
                model_str: Optional[str] = None) -> ModelVersion:
        """Flatten + pre-warm + atomically swap in a new version.
        Accepts a live :class:`~lightgbm_tpu.basic.Booster`, a model
        file path, or a model string."""
        with self._publish_lock:
            if booster is None:
                from ..basic import Booster
                booster = Booster(model_file=model_file,
                                  model_str=model_str)
            ver = ModelVersion(self._next_version, booster,
                               self.chunk_rows,
                               fastpath_rows=self.fastpath_rows)
            if self.warm:
                info = ver.warmup()
                Log.info("serve: warmed model v%d (%d trees) — "
                         "buckets %s, %d compiles, %.2fs",
                         ver.version, ver.n_trees,
                         info["buckets"], int(info["xla_compiles"]),
                         info["warmup_s"])
            with self._lock:
                self._active = ver
                self._next_version += 1
                self._history.append(ver.meta())
                del self._history[:-16]
            _tele_counters.incr("serve_swaps")
            return ver

    def publish_from_checkpoint(self, path: str) -> ModelVersion:
        """Hot-swap straight from a TRAINING checkpoint directory
        (``lightgbm_tpu/ckpt/``): accepts one finalized ``ckpt_*``
        directory or a checkpoint root, where the newest VALID
        snapshot wins — corrupt/truncated candidates are skipped with
        the loader's fallback telemetry, so a serving tier pointed at
        a live training job's checkpoint_dir always publishes a
        loadable model.  The checkpoint's ``model.txt`` (validated
        against the manifest's content hash) becomes a Booster and
        goes through the normal flatten -> pre-warm -> atomic swap
        publish."""
        import os

        from ..ckpt import CheckpointError, CheckpointManager
        path = str(path)
        explicit = CheckpointManager.is_checkpoint_dir(path)
        model_str = None
        ckpt = None
        # a live trainer re-saving the same boundary swaps the dir
        # out from under us between validate and read (os.replace to
        # .old, then the fresh dir in) — retry the scan on OSError
        # instead of crashing the publish
        for attempt in range(3):
            if explicit:
                errs = CheckpointManager.validate(path)
                if errs:
                    raise CheckpointError(f"{path}: " + "; ".join(errs))
                ckpt = path
            else:
                ckpt = CheckpointManager(path).newest_valid()
                if ckpt is None:
                    raise CheckpointError(
                        f"{path}: no valid checkpoint to publish")
            try:
                with open(os.path.join(ckpt, "model.txt")) as f:
                    model_str = f.read()
                break
            except OSError as exc:
                if attempt == 2:
                    raise CheckpointError(
                        f"{ckpt}: checkpoint disappeared mid-publish "
                        f"({exc})")
                time.sleep(0.05)
        ver = self.publish(model_str=model_str)
        Log.info("serve: published v%d from checkpoint %s",
                 ver.version, ckpt)
        return ver

    # -- lookup ----------------------------------------------------------
    def current(self) -> Optional[ModelVersion]:
        with self._lock:
            return self._active

    def require(self) -> ModelVersion:
        ver = self.current()
        if ver is None:
            from .admission import ServeError
            raise ServeError("no model published to the registry")
        return ver

    def history(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._history)
