"""Admission control: bounded request queue with priority load-shedding.

The serving front admits a request only when the queue has room —
bounded both in ROWS (the real resource: device batch slots) and in
request count.  A saturated queue rejects with a retry-after hint
(backpressure the HTTP front surfaces as ``Retry-After``), unless the
incoming request outranks pending work, in which case the
lowest-priority most-recently-admitted pending request is shed instead
(graceful degradation: cheap traffic is dropped first, high-priority
traffic keeps its latency).  Expired requests are swept at drain time
so a stale deadline never wastes a device dispatch.

The queue is also the coalescing point: :meth:`AdmissionQueue.drain_batch`
blocks until work is available, gives concurrent submitters
``batch_wait`` to pile on, then hands the dispatcher a FIFO run of
same-version same-kind requests totalling at most ``max_batch_rows``
rows (version grouping is what lets a hot-swap proceed while
old-version requests are still in flight; kind grouping keeps the
predict and explain lanes in separate device batches).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np


class ServeError(RuntimeError):
    """Base class of serving-front errors."""


class QueueSaturated(ServeError):
    """Admission rejected: queue full (backpressure)."""

    def __init__(self, msg: str, retry_after_ms: float = 0.0):
        super().__init__(msg)
        self.retry_after_ms = float(retry_after_ms)


class RequestShed(ServeError):
    """Request was load-shed by a higher-priority admission."""


class RequestTimeout(ServeError):
    """Request deadline expired before completion."""


class ServerClosed(ServeError):
    """Server is not accepting requests."""


class UnknownModel(ServeError):
    """Request names a model the tenancy table has no registry for
    (HTTP 404 — distinct from 429 budget shed and 503 no-backend)."""


class Request:
    """One predict or explain request; completion is an event the
    submitting thread (or HTTP handler) waits on.  ``version`` is
    pinned at ADMISSION — a later hot-swap never changes which model
    this request is scored by.  ``kind`` ("predict" | "explain")
    selects the dispatch lane: the coalescer groups by (version,
    kind) identity, so predict and explain rows never share a device
    batch."""

    __slots__ = ("rid", "X", "raw", "priority", "deadline", "t_admit",
                 "version", "kind", "status", "result", "error",
                 "retry_after_ms", "timings", "trace", "_done",
                 "_finish_lock")

    def __init__(self, rid: int, X: np.ndarray, raw: bool,
                 priority: int, deadline: Optional[float], version,
                 kind: str = "predict"):
        self.rid = rid
        self.X = X
        self.raw = bool(raw)
        self.kind = str(kind)
        self.priority = int(priority)
        self.deadline = deadline        # absolute time.monotonic(), or None
        self.t_admit = time.monotonic()
        self.version = version          # ModelVersion pinned at admission
        self.status = "pending"         # -> ok|shed|timeout|rejected|error
        self.result: Optional[np.ndarray] = None
        self.error: Optional[str] = None
        self.retry_after_ms = 0.0
        self.timings: Dict[str, float] = {}
        # (trace_id, span_id) captured at admission: the serve record
        # is emitted on a DISPATCHER thread, where the submitter's
        # contextvar is not visible (obs/spans.py)
        self.trace = None
        self._done = threading.Event()
        self._finish_lock = threading.Lock()

    @property
    def rows(self) -> int:
        return int(self.X.shape[0])

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (time.monotonic() if now is None else now) >= self.deadline

    # -- completion ------------------------------------------------------
    def finish(self, status: str, result: Optional[np.ndarray] = None,
               error: Optional[str] = None,
               retry_after_ms: float = 0.0) -> bool:
        """Complete the request; FIRST writer wins (the dispatcher and
        the wedged-worker guard can race).  Returns False when the
        request was already finished — the caller must then skip its
        telemetry emit, or one request double-counts."""
        with self._finish_lock:
            if self._done.is_set():
                return False
            self.status = status
            self.result = result
            self.error = error
            self.retry_after_ms = float(retry_after_ms)
            self.timings.setdefault(
                "total_ms", (time.monotonic() - self.t_admit) * 1e3)
            self._done.set()
        return True

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def value(self) -> np.ndarray:
        """Block for the result or raise the failure (the Python-API
        surface; the HTTP front maps these to status codes)."""
        self._done.wait()
        if self.status == "ok":
            return self.result
        if self.status == "timeout":
            raise RequestTimeout(self.error or "request timed out")
        if self.status == "shed":
            raise RequestShed(self.error or "request shed under load")
        if self.status == "rejected":
            raise QueueSaturated(self.error or "queue saturated",
                                 self.retry_after_ms)
        raise ServeError(self.error or f"request failed ({self.status})")


class AdmissionQueue:
    """Bounded FIFO with priority shedding and batch coalescing."""

    def __init__(self, max_rows: int, max_requests: int,
                 batch_rows_hint: int = 1024):
        self.max_rows = int(max_rows)
        self.max_requests = int(max_requests)
        self.batch_rows_hint = max(int(batch_rows_hint), 1)
        self.cond = threading.Condition()
        self._dq: "deque[Request]" = deque()
        self._rows = 0
        self._closed = False
        # EWMA of batch service time, maintained by the dispatcher —
        # the retry-after hint converts backlog depth into milliseconds
        self.service_ms_hint = 10.0

    # -- introspection ---------------------------------------------------
    def depth(self) -> Tuple[int, int]:
        with self.cond:
            return len(self._dq), self._rows

    def closed(self) -> bool:
        return self._closed

    def retry_after_ms(self) -> float:
        # backlog in batches (plus the one being formed) x service EWMA
        batches = self._rows / self.batch_rows_hint + 1.0
        return round(batches * max(self.service_ms_hint, 1.0), 1)

    # -- admission -------------------------------------------------------
    def admit(self, req: Request) -> List[Request]:
        """Admit ``req`` or raise :class:`QueueSaturated`.  Returns the
        requests shed to make room (already finished with status
        ``shed``; the caller emits their telemetry)."""
        shed: List[Request] = []
        with self.cond:
            if self._closed:
                raise ServerClosed("server is shutting down")
            # an oversize request on an EMPTY queue is always admitted
            # (it could never fit otherwise); the engine chunks it
            while self._dq and (
                    self._rows + req.rows > self.max_rows or
                    len(self._dq) + 1 > self.max_requests):
                victim = self._lowest_priority_below(req.priority)
                if victim is None:
                    raise QueueSaturated(
                        f"queue saturated ({len(self._dq)} requests / "
                        f"{self._rows} rows pending)",
                        self.retry_after_ms())
                self._dq.remove(victim)
                self._rows -= victim.rows
                shed.append(victim)
            self._dq.append(req)
            self._rows += req.rows
            self.cond.notify_all()
        for v in shed:
            v.finish("shed", error="shed by higher-priority admission")
        return shed

    def _lowest_priority_below(self, priority: int) -> Optional[Request]:
        """The shedding victim: lowest priority strictly below the
        incoming one; ties broken toward the MOST RECENT admission
        (oldest work keeps its place)."""
        victim = None
        for r in self._dq:
            if r.priority >= priority:
                continue
            if victim is None or r.priority <= victim.priority:
                victim = r
        return victim

    # -- coalescing drain ------------------------------------------------
    def drain_batch(self, max_batch_rows: int, wait_s: float,
                    stop: threading.Event
                    ) -> Tuple[List[Request], List[Request]]:
        """Coalesce the next batch.  Returns ``(batch, timed_out)``;
        ``timed_out`` requests are already finished (status
        ``timeout``) — the caller emits their telemetry.  Returns
        ``([], [])`` when stopped/closed with an empty queue."""
        timed: List[Request] = []
        out: List[Request] = []
        with self.cond:
            while not self._dq:
                if stop.is_set() or self._closed:
                    return [], []
                self.cond.wait(0.05)
            head = self._dq[0]
            # coalescing window: concurrent submitters get wait_s
            # (counted from the OLDEST pending admission) to pile on
            t_dead = head.t_admit + wait_s
            while (not stop.is_set()
                   and self._front_rows(head.version,
                                        head.kind) < max_batch_rows):
                left = t_dead - time.monotonic()
                if left <= 0:
                    break
                self.cond.wait(left)
            now = time.monotonic()
            rows = 0
            while self._dq:
                r = self._dq[0]
                if r.expired(now):
                    self._dq.popleft()
                    self._rows -= r.rows
                    timed.append(r)
                    continue
                if out and (r.version is not out[0].version or
                            r.kind != out[0].kind or
                            rows + r.rows > max_batch_rows):
                    break
                self._dq.popleft()
                self._rows -= r.rows
                out.append(r)
                rows += r.rows
                if rows >= max_batch_rows:
                    break
            self.cond.notify_all()
        for t in timed:
            t.finish("timeout", error="deadline expired in queue")
        return out, timed

    def _front_rows(self, version, kind: str = "predict") -> int:
        """Rows in the batchable FIFO prefix (same version AND kind,
        capped scan — the queue bound keeps this short)."""
        rows = 0
        for i, r in enumerate(self._dq):
            if r.version is not version or r.kind != kind or i >= 512:
                break
            rows += r.rows
        return rows

    def close(self) -> None:
        with self.cond:
            self._closed = True
            self.cond.notify_all()
