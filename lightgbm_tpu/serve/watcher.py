"""Checkpoint-root watcher: continuous deployment with a safety gate,
plus the telemetry-driven rollback controller.

``CheckpointWatcher`` polls a training job's ``checkpoint_dir`` for
new finalized ``ckpt_*`` snapshots (``ckpt/manager.py`` write
protocol: a finalized name implies a complete directory) and runs each
one through a validation pipeline BEFORE it can reach the serving
tier:

1. **manifest verify** — every blob must match its manifested size and
   sha256 (``CheckpointManager.validate``); a corrupt/truncated
   snapshot is skipped with a ``fleet``/``publish_skip`` telemetry
   record (``reason=manifest``) and the previous version keeps
   serving.
2. **canary scoring** — the snapshot's model scores pinned reference
   rows (:class:`CanarySet`): predictions must be finite, match
   pinned ``expected`` outputs within tolerance when given, and clear
   a label-AUC quality bar when given.  A mis-scoring model is skipped
   (``reason=canary``) — it parsed fine, it is just WRONG, which no
   hash can catch.
3. **publish** — only then does the model text go to the publish
   target (an in-process :class:`RegistryTarget` or the whole fleet
   via :class:`FleetTarget` -> ``FleetSupervisor.publish_model``).

After every publish the **rollback controller** watches the serve
telemetry rollups: once the observation window has both elapsed
(``rollback_window_s``) and seen ``rollback_min_requests`` requests,
the post-publish bad-request rate (shed/timeout/error per request) and
p99 latency are compared against the pre-publish window.  A regression
republishes the pre-publish model (captured in memory at publish time,
independent of checkpoint retention pruning) and puts the bad model's
fingerprint in hold-down so it cannot flap back in.  ``rollback``
records and skips surface as triage anomalies
(``tools/triage_run.py``).

Fault-injection points: ``watcher.validate`` (mode ``reject``) and
``watcher.canary`` (mode ``fail``) force each skip path — the CI chaos
job drives both (``utils/faults.py``).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import json as _json

from ..ckpt.manager import CheckpointManager
from ..obs import spans as _spans
from ..utils import faults as _faults
from ..utils.log import Log
from .config import FleetConfig
from .registry import model_fingerprint

__all__ = ["CanarySet", "CheckpointWatcher", "RegistryTarget",
           "FleetTarget", "auc_score"]


def auc_score(labels, scores) -> float:
    """Rank-based AUC (ties averaged) — the canary quality gate's
    metric, dependency-free."""
    labels = np.asarray(labels, np.float64).ravel()
    scores = np.asarray(scores, np.float64).ravel()
    pos = labels > 0
    n_pos = int(pos.sum())
    n_neg = labels.size - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(labels.size, np.float64)
    ranks[order] = np.arange(1, labels.size + 1)
    # average ranks across tied scores so the gate is permutation-stable
    sorted_scores = scores[order]
    i = 0
    while i < labels.size:
        j = i
        while j + 1 < labels.size and \
                sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = 0.5 * (i + 1 + j + 1)
        i = j + 1
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * n_neg))


class CanarySet:
    """Pinned reference rows every candidate snapshot must score
    correctly before publishing.

    - ``expected`` (optional): predictions pinned within ``tol``
      (relative+absolute) — the bit-rot / wrong-artifact detector.
    - ``labels`` + ``min_auc`` (optional): a quality gate that holds
      across retrains — a newly trained (different) model passes as
      long as it actually ranks the canary rows.
    """

    def __init__(self, X, expected=None, labels=None,
                 min_auc: float = 0.0, tol: float = 1e-6):
        self.X = np.ascontiguousarray(np.asarray(X, np.float64))
        if self.X.ndim != 2 or self.X.shape[0] == 0:
            raise ValueError("canary X must be a non-empty 2-D matrix")
        self.expected = None if expected is None else \
            np.asarray(expected, np.float64)
        self.labels = None if labels is None else \
            np.asarray(labels, np.float64).ravel()
        if self.labels is not None and \
                self.labels.size != self.X.shape[0]:
            raise ValueError("canary labels length != rows")
        self.min_auc = float(min_auc)
        self.tol = float(tol)

    @classmethod
    def from_file(cls, path: str, min_auc: float = 0.0,
                  tol: float = 1e-6) -> "CanarySet":
        """Load ``canary_file``: npz with ``X`` and optional
        ``expected`` / ``label`` arrays."""
        with np.load(path) as z:
            X = z["X"]
            expected = z["expected"] if "expected" in z.files else None
            labels = z["label"] if "label" in z.files else (
                z["labels"] if "labels" in z.files else None)
        return cls(X, expected=expected, labels=labels,
                   min_auc=min_auc, tol=tol)

    def check(self, booster) -> List[str]:
        """Score the canary rows; returns problems (empty = pass)."""
        errs: List[str] = []
        try:
            preds = np.asarray(booster.predict(self.X), np.float64)
        except Exception as exc:           # noqa: BLE001 - model's fault
            return [f"canary predict raised: {exc}"]
        if _faults.fire("watcher.canary") == "fail":
            errs.append("injected fault (watcher.canary:fail)")
        if not np.all(np.isfinite(preds)):
            errs.append(f"canary predictions contain "
                        f"{int((~np.isfinite(preds)).sum())} "
                        f"non-finite values")
        if self.expected is not None:
            if preds.shape != self.expected.shape:
                errs.append(f"canary shape {preds.shape} != expected "
                            f"{self.expected.shape}")
            elif not np.allclose(preds, self.expected, rtol=self.tol,
                                 atol=self.tol):
                worst = float(np.max(np.abs(preds - self.expected)))
                errs.append(f"canary predictions deviate from pinned "
                            f"expected outputs (max abs diff "
                            f"{worst:.3g} > tol {self.tol:g})")
        if self.labels is not None and self.min_auc > 0 \
                and preds.ndim == 1:
            auc = auc_score(self.labels, preds)
            if auc < self.min_auc:
                errs.append(f"canary AUC {auc:.4f} below the "
                            f"canary_min_auc={self.min_auc:g} quality "
                            f"bar")
        return errs


# ----------------------------------------------------------------------
# publish targets
# ----------------------------------------------------------------------
class RegistryTarget:
    """Publish target over one in-process :class:`~.server.Server`.

    ``model`` names the tenant registry published into (None/"default"
    = the unnamed single-model routes) — the watcher's end-to-end
    named-tenant path: daemon checkpoint (or sweep winner) -> named
    registry -> ``/v1/<model>/predict``."""

    def __init__(self, server, model: Optional[str] = None):
        self.server = server
        self.model = model if model not in (None, "") else None

    def _registry(self):
        try:
            return self.server.registry_for(self.model)
        except Exception:          # noqa: BLE001 - tenant not yet born
            return None

    def active_model(self) -> Optional[Tuple[str, str]]:
        reg = self._registry()
        ver = reg.current() if reg is not None else None
        return None if ver is None else (ver.model_id, ver.model_text)

    def publish_model(self, model_text: str, source: str = "") -> str:
        self.server.swap(model_str=model_text, model=self.model)
        return self.server.registry_for(self.model).current().model_id

    def active_ids(self) -> List[str]:
        reg = self._registry()
        ver = reg.current() if reg is not None else None
        return [] if ver is None else [ver.model_id]

    def stats_probe(self) -> Dict[str, float]:
        s = self.server.stats()
        counts = s.get("requests") or {}
        return {
            "requests": float(sum(int(v) for v in counts.values())),
            "bad": float(sum(int(counts.get(k, 0))
                             for k in ("shed", "timeout", "error"))),
            "p99_ms": float((s.get("latency_ms") or {})
                            .get("p99", 0.0)),
        }


class FleetTarget:
    """Publish target over a :class:`~.fleet.FleetSupervisor`: publish
    swaps every healthy replica (the supervisor reconciles restarts),
    probes aggregate across the fleet."""

    def __init__(self, supervisor, model: Optional[str] = None):
        self.supervisor = supervisor
        self.model = model if model not in (None, "") else "default"

    def active_model(self) -> Optional[Tuple[str, str]]:
        import json as _json
        import urllib.request
        route = "/model" if self.model == "default" else \
            f"/v1/{self.model}/model"
        for url in self.supervisor.endpoints():
            try:
                with urllib.request.urlopen(url + route,
                                            timeout=10) as r:
                    obj = _json.loads(r.read())
                return obj["model_id"], obj["model_str"]
            except Exception:              # noqa: BLE001 - try the next
                continue
        return None

    def publish_model(self, model_text: str, source: str = "") -> str:
        return self.supervisor.publish_model(model_text, source,
                                             model=self.model)

    def active_ids(self) -> List[str]:
        return [mid for mid in
                self.supervisor.active_models(self.model).values()
                if mid is not None]

    def stats_probe(self) -> Dict[str, float]:
        return self.supervisor.stats_probe()


# ----------------------------------------------------------------------
# the watcher + rollback controller
# ----------------------------------------------------------------------
class CheckpointWatcher:
    """Polls a checkpoint root, validates, canaries, publishes, and
    rolls back regressions.  ``poll_once()`` is the deterministic unit
    tests drive directly; ``start()`` runs it on a daemon thread every
    ``watch_poll_s``."""

    def __init__(self, root: str, target,
                 config: Optional[FleetConfig] = None,
                 canary: Optional[CanarySet] = None, recorder=None):
        self.root = str(root)
        self.target = target
        self.config = config or FleetConfig()
        self.canary = canary
        if self.canary is None and self.config.canary_file:
            self.canary = CanarySet.from_file(
                self.config.canary_file,
                min_auc=self.config.canary_min_auc,
                tol=self.config.canary_tolerance)
        self.recorder = recorder
        self.mgr = CheckpointManager(self.root)
        self._last_iter = -1
        # fingerprint of the last same-boundary RE-SAVE examined: the
        # continual daemon's refit batches recalibrate leaf values
        # without advancing the iteration, re-saving the newest
        # ckpt_* in place — the content change, not a new name, is
        # the publish trigger (and a canary-failing re-save must not
        # be retried every poll).  The stat gate keeps quiescent polls
        # from re-reading the model text every tick.
        self._resave_seen: Optional[str] = None
        self._resave_stat: Optional[Tuple[str, int, int]] = None
        self._holddown: Dict[str, float] = {}  # model_id -> until (mono)
        self._baseline: Optional[Tuple[str, str]] = None
        self._watchdog: Optional[Dict[str, Any]] = None
        self._probes: "deque[Tuple[float, Dict[str, float]]]" = \
            deque(maxlen=256)
        self._published: List[Dict[str, Any]] = []   # audit trail
        self._last_prev: Optional[Tuple[str, str]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "CheckpointWatcher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="ltpu-watcher",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.watch_poll_s):
            try:
                self.poll_once()
            except Exception as exc:       # noqa: BLE001 - keep polling
                Log.warning("watcher: poll failed: %s", exc)
                self._emit("watch_error", error=str(exc)[:200])

    # -- telemetry -----------------------------------------------------
    def _emit(self, event: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.emit("fleet", event=event, **fields)

    # -- one poll ------------------------------------------------------
    def poll_once(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        try:
            self._probes.append((now, self.target.stats_probe()))
        except Exception:                  # noqa: BLE001 - fleet warming
            pass
        self._check_watchdog(now)
        if self._baseline is None:
            try:
                self._baseline = self.target.active_model()
            except Exception:              # noqa: BLE001
                pass
        fresh = False
        for iter_, path in self.mgr.candidates():
            if iter_ <= self._last_iter:
                continue
            # publishing is sequential: while a deploy is under
            # observation, newer snapshots wait their turn (a rollback
            # must restore a KNOWN-good version, not race a new one)
            if self._watchdog is not None:
                break
            self._process(iter_, path, now)
            fresh = True
        if self._watchdog is None and not fresh:
            self._check_resave(now)

    def _check_resave(self, now: float) -> None:
        """Re-examine the NEWEST already-seen checkpoint: a continual
        refit re-saves the current boundary with new leaf values under
        the same ``ckpt_*`` name, so the fingerprint change is what
        must go through the manifest+canary gate.  Each distinct
        re-save content is examined once (a canary-failing refit is
        not retried every poll)."""
        cands = self.mgr.candidates()
        if not cands or cands[-1][0] != self._last_iter:
            return
        iter_, path = cands[-1]
        mpath = os.path.join(path, "model.txt")
        try:
            st = os.stat(mpath)
            stat_key = (path, st.st_mtime_ns, st.st_size)
        except OSError:
            return
        if stat_key == self._resave_stat:
            # unchanged since the last idle poll: don't re-read and
            # re-hash a potentially large model text every watch tick
            return
        try:
            with open(mpath) as f:
                mid = model_fingerprint(f.read())
        except OSError:
            return     # racing a re-save swap: re-stat next poll
        self._resave_stat = stat_key
        if mid == self._resave_seen:
            return
        active = None
        try:
            active = self.target.active_model()
        except Exception:                  # noqa: BLE001
            pass
        self._resave_seen = mid
        if active is not None and active[0] == mid:
            return
        self._process(iter_, path, now)

    @staticmethod
    def _snapshot_trace(path: str):
        """The trace carrier the saving process recorded in
        ``extra.json`` (``ckpt/manager.py``) — how the daemon's
        ingest->train->checkpoint trace continues through this
        watcher's validate->canary->publish, across OS processes."""
        try:
            with open(os.path.join(path, "extra.json")) as f:
                return _spans.parse((_json.load(f) or {}).get("trace"))
        except Exception:                  # noqa: BLE001 - optional
            return None

    def _process(self, iter_: int, path: str, now: float) -> None:
        with _spans.use(self._snapshot_trace(path)):
            self._process_in_trace(iter_, path, now)

    def _process_in_trace(self, iter_: int, path: str,
                          now: float) -> None:
        self._last_iter = iter_            # a bad snapshot is not retried
        name = os.path.basename(path)
        with _spans.span("watcher_validate", recorder=self.recorder,
                         path=name) as sp:
            mode = _faults.fire("watcher.validate")
            errs = ["injected fault (watcher.validate:reject)"] \
                if mode == "reject" else CheckpointManager.validate(path)
            sp.set(errors=len(errs))
        if errs:
            msg = "; ".join(errs)[:300]
            Log.warning("watcher: SKIP %s — manifest validation "
                        "failed: %s", name, msg)
            self._emit("publish_skip", reason="manifest", path=name,
                       iter=iter_, error=msg)
            return
        try:
            with open(os.path.join(path, "model.txt")) as f:
                model_text = f.read()
        except OSError as exc:
            self._emit("publish_skip", reason="manifest", path=name,
                       iter=iter_, error=f"model.txt unreadable: {exc}")
            return
        mid = model_fingerprint(model_text)
        self._resave_seen = mid        # _check_resave examines once
        until = self._holddown.get(mid, 0.0)
        if until > now:
            Log.warning("watcher: SKIP %s — model %s is in rollback "
                        "hold-down for %.0fs more", name, mid,
                        until - now)
            self._emit("publish_skip", reason="holddown", path=name,
                       iter=iter_, model_id=mid)
            return
        active = None
        try:
            active = self.target.active_model()
        except Exception:                  # noqa: BLE001
            pass
        if active is not None and active[0] == mid:
            return                         # already serving this model
        if self.canary is not None:
            from ..basic import Booster
            with _spans.span("watcher_canary", recorder=self.recorder,
                             path=name, model_id=mid) as sp:
                try:
                    booster = Booster(model_str=model_text)
                except Exception as exc:   # noqa: BLE001 - bad model
                    sp.set(parse_failed=True)
                    self._emit("publish_skip", reason="canary",
                               path=name, iter=iter_,
                               error=f"model parse failed: "
                                     f"{exc}"[:300])
                    return
                errs = self.canary.check(booster)
                sp.set(errors=len(errs))
            if errs:
                msg = "; ".join(errs)[:300]
                Log.warning("watcher: SKIP %s — canary failed: %s",
                            name, msg)
                self._emit("publish_skip", reason="canary", path=name,
                           iter=iter_, model_id=mid, error=msg)
                return
        # pre-publish capture: the window stats AND the version to
        # roll back to (kept in memory — immune to checkpoint
        # retention pruning the previous snapshot directory)
        try:
            pre = self.target.stats_probe()
        except Exception:                  # noqa: BLE001
            pre = {"requests": 0.0, "bad": 0.0, "p99_ms": 0.0}
        prev = active if active is not None else self._baseline
        t0 = time.monotonic()
        try:
            # inside the publish span the fleet's /swap requests (and
            # through them each replica's first served request) carry
            # the trace that began at the daemon's batch root
            with _spans.span("publish", recorder=self.recorder,
                             path=name, model_id=mid):
                pub_id = self.target.publish_model(model_text,
                                                   source=path)
        except Exception as exc:           # noqa: BLE001 - target down
            Log.warning("watcher: publish of %s failed: %s", name, exc)
            self._emit("publish_skip", reason="error", path=name,
                       iter=iter_, model_id=mid,
                       error=str(exc)[:300])
            return
        self._emit("publish", path=name, iter=iter_, model_id=pub_id,
                   duration_ms=round((time.monotonic() - t0) * 1e3, 3))
        Log.info("watcher: published %s (model %s)", name, pub_id)
        self._published.append({"path": path, "iter": iter_,
                                "model_id": pub_id})
        self._last_prev = prev             # force_rollback's target
        self._watchdog = {
            "model_id": pub_id, "model_text": model_text,
            "published_at": now, "pre": pre,
            "pre_rate": self._window_rate_before(now, pre),
            "prev": prev, "path": name,
        }

    # -- rollback controller -------------------------------------------
    def _window_rate_before(self, now: float,
                            pre: Dict[str, float]) -> float:
        """Bad-request rate over the window BEFORE ``now``: the
        current cumulative probe diffed against the probe closest to
        one observation window ago."""
        target_t = now - self.config.rollback_window_s
        older = None
        for t, probe in self._probes:
            if t <= target_t:
                older = probe
            else:
                break
        if older is None and self._probes:
            older = self._probes[0][1]
        if older is None:
            return 0.0
        dreq = pre["requests"] - older["requests"]
        dbad = pre["bad"] - older["bad"]
        return (dbad / dreq) if dreq > 0 else 0.0

    def _check_watchdog(self, now: float) -> None:
        wd = self._watchdog
        if wd is None:
            return
        cfg = self.config
        elapsed = now - wd["published_at"]
        if elapsed < cfg.rollback_window_s:
            return
        try:
            post = self.target.stats_probe()
        except Exception:                  # noqa: BLE001
            return
        dreq = post["requests"] - wd["pre"]["requests"]
        dbad = post["bad"] - wd["pre"]["bad"]
        if dreq < 0 or dbad < 0:
            # cumulative counters went BACKWARDS: replicas crashed and
            # restarted after the publish — that is itself the
            # regression signal (and the deltas below would be garbage)
            self._rollback(wd, "stats_reset",
                           "serve counters went backwards (replica "
                           "crash/restart after the publish)", now)
            return
        if dreq < cfg.rollback_min_requests:
            if elapsed < 4 * cfg.rollback_window_s:
                return                     # not enough evidence yet
            # evidence never arrived (idle fleet, or the deploy killed
            # traffic entirely): do NOT bless the deploy — release the
            # pipeline but keep the previous version as the rollback
            # baseline/target
            self._watchdog = None
            self._emit("publish_unverified", model_id=wd["model_id"],
                       path=wd["path"], window_requests=int(dreq))
            Log.warning("watcher: deploy %s UNVERIFIED — only %d "
                        "requests in %.0fs of observation; the "
                        "previous version stays the rollback baseline",
                        wd["model_id"], int(dreq), elapsed)
            return
        post_rate = dbad / dreq
        pre_rate = wd["pre_rate"]
        pre_p99 = wd["pre"]["p99_ms"]
        post_p99 = post["p99_ms"]
        reason = None
        if post_rate > pre_rate + cfg.rollback_error_rate:
            reason = "error_rate"
        elif post_p99 > cfg.rollback_p99_floor_ms and \
                post_p99 > cfg.rollback_p99_factor * max(pre_p99, 0.1):
            reason = "p99"
        if reason is None:
            self._watchdog = None
            self._baseline = (wd["model_id"], wd["model_text"])
            self._emit("publish_verified", model_id=wd["model_id"],
                       path=wd["path"], window_requests=int(dreq),
                       bad_rate=round(post_rate, 4),
                       p99_ms=round(post_p99, 3))
            Log.info("watcher: deploy %s verified (%d requests, bad "
                     "rate %.3f, p99 %.1f ms)", wd["model_id"],
                     int(dreq), post_rate, post_p99)
            return
        detail = (f"bad rate {post_rate:.3f} vs pre {pre_rate:.3f}"
                  if reason == "error_rate" else
                  f"p99 {post_p99:.1f} ms vs pre {pre_p99:.1f} ms")
        self._rollback(wd, reason, detail, now)

    def _rollback(self, wd: Dict[str, Any], reason: str, detail: str,
                  now: float) -> None:
        prev = wd.get("prev")
        self._watchdog = None
        self._holddown[wd["model_id"]] = \
            now + self.config.rollback_holddown_s
        if prev is None:
            Log.warning("watcher: deploy %s regressed (%s) but no "
                        "previous version is known — cannot roll back",
                        wd["model_id"], detail)
            self._emit("watch_error",
                       error=f"regression ({reason}: {detail}) with "
                             f"no rollback target")
            return
        prev_id, prev_text = prev
        try:
            self.target.publish_model(prev_text, source="rollback")
        except Exception as exc:           # noqa: BLE001
            Log.warning("watcher: ROLLBACK of %s failed: %s",
                        wd["model_id"], exc)
            self._emit("watch_error",
                       error=f"rollback publish failed: {exc}"[:300])
            return
        self._baseline = prev
        self._emit("rollback", reason=reason, detail=detail[:200],
                   from_id=wd["model_id"], to_id=prev_id,
                   path=wd.get("path"))
        Log.warning("watcher: ROLLED BACK deploy %s -> %s (%s: %s)",
                    wd["model_id"], prev_id, reason, detail)

    def force_rollback(self, reason: str = "forced") -> bool:
        """Operator-commanded rollback: undo the deploy under
        observation, or — with none pending — republish the version
        that was serving BEFORE the last publish (even one that
        already verified clean).  Returns True if a republish
        happened."""
        now = time.monotonic()
        if self._watchdog is not None:
            self._rollback(self._watchdog, reason, "operator command",
                           now)
            return True
        try:
            active = self.target.active_model()
        except Exception:                  # noqa: BLE001
            active = None
        prev = self._last_prev or self._baseline
        if prev is not None and active is not None and \
                active[0] != prev[0]:
            self.target.publish_model(prev[1], source="rollback")
            self._holddown[active[0]] = \
                now + self.config.rollback_holddown_s
            self._baseline = prev
            self._emit("rollback", reason=reason,
                       detail="operator command",
                       from_id=active[0], to_id=prev[0])
            Log.warning("watcher: FORCED rollback %s -> %s",
                        active[0], prev[0])
            return True
        return False
