"""The serving front: dispatcher loop + in-process Python API.

``Server(booster)`` owns the admission queue, the micro-batcher, the
model registry and the dispatcher thread(s); ``predict()`` is the
blocking client surface (``submit()`` returns the request future).
Every request — completed, shed, timed out or rejected — feeds one
``serve`` telemetry record (``utils/telemetry.py``) carrying the
queue-wait / batch-assembly / dispatch / total latency split, the
batch occupancy, and the version that scored it; the recorder's
``run_end`` summary rolls up p50/p95/p99 latency and shed/timeout
counts.  Steady-state serving re-runs only cached XLA programs: the
batcher packs to warmed buckets and swaps pre-warm off the request
path, so the ``xla_compiles`` counter stays flat after warmup (pinned
in ``tests/test_serve.py``).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import metrics as _obs_metrics
from ..obs import spans as _spans
from ..utils import faults as _faults
from ..utils.log import Log
from ..utils.telemetry import counters as _tele_counters
from .admission import (AdmissionQueue, QueueSaturated, Request,
                        ServerClosed, UnknownModel)
from .batcher import Batch, MicroBatcher
from .config import ServeConfig
from .registry import ModelRegistry

#: the registry name un-prefixed routes (``/predict``, ``/swap``)
#: resolve to; named tenants ride ``/v1/<model>/...``
DEFAULT_MODEL = "default"


class Server:
    """In-process online predict server over the jitted engine."""

    def __init__(self, booster=None,
                 params: Optional[Dict[str, Any]] = None,
                 config: Optional[ServeConfig] = None,
                 telemetry=None):
        self.config = config or ServeConfig.from_params(params)
        self.config.validate()
        self.queue = AdmissionQueue(
            self.config.queue_rows, self.config.queue_requests,
            batch_rows_hint=self.config.max_batch_rows)
        self.batcher = MicroBatcher(self.queue, self.config)
        # multi-model tenancy: one ModelRegistry per named model, all
        # sharing this server's queue/batcher/dispatchers (requests pin
        # their ModelVersion at admission and the batcher groups by
        # version identity, so tenants never mix in a device batch).
        # ``registry`` stays the default tenant for the single-model
        # API surface.
        self._registries: Dict[str, ModelRegistry] = {
            DEFAULT_MODEL: ModelRegistry(
                chunk_rows=self.config.max_batch_rows,
                warm=self.config.warmup,
                fastpath_rows=self.config.fastpath_max_rows)}
        self._registries_lock = threading.Lock()
        self._stop = threading.Event()
        self.draining = False
        self._threads: List[threading.Thread] = []
        self._rid = 0
        self._rid_lock = threading.Lock()
        # bounded ROLLING histogram (obs/metrics.py): /stats
        # percentiles come from fixed buckets over the last one-to-
        # two minutes, so a long-lived replica's stats memory is O(1)
        # AND its p99 reflects current behavior — the rollback
        # watchdog compares p99 across a deploy, which a lifetime
        # histogram would dilute on a replica with request history.
        # Kept SEPARATE from the registry's ltpu_serve_latency_ms on
        # purpose: /stats is per-server and recency-windowed, the
        # registry series is process-wide and cumulative (Prometheus
        # scrapers window buckets themselves)
        lat_buckets = self.config.metrics_latency_buckets or \
            _obs_metrics.DEFAULT_LATENCY_BUCKETS_MS
        self._lat_hist = _obs_metrics.RollingHistogram(
            buckets=lat_buckets)
        self._counts: Dict[str, int] = {}
        self._counts_lock = threading.Lock()
        self._metrics = self._make_metrics(lat_buckets) \
            if self.config.metrics else None
        self._recorder = self._make_recorder(telemetry)
        self._owns_recorder = telemetry is None and \
            self._recorder is not None
        # the serve path scores through the engine directly (pinned
        # flat tables, not GBDT.predict_raw), so the LRU-capacity knob
        # must be applied here — GBDT._engine() never runs
        if self.config.predict_cache_slots > 0:
            from ..ops.predict import get_engine
            from ..ops.shap import get_shap_engine
            get_engine().set_cache_size(self.config.predict_cache_slots)
            # the explanation engine shares the LRU-capacity contract:
            # its serve-visible layouts x buckets must stay resident
            # or steady-state explains would recompile
            get_shap_engine().set_cache_size(
                self.config.predict_cache_slots)
        if booster is not None:
            self.registry.publish(booster)

    def _make_metrics(self, lat_buckets) -> Dict[str, Any]:
        """Register this server's live-metrics series (GET /metrics).
        Counters/histograms are process-wide and fed at the SAME call
        sites as the telemetry records, so the scrape matches the
        run_end rollups bit-for-bit; gauges are scrape-time callbacks
        re-pointed at the newest server."""
        _obs_metrics.install_telemetry_mirror()
        reg = _obs_metrics.get_registry()
        m = {
            "requests": reg.counter(
                "ltpu_serve_requests_total",
                "serve requests by terminal status", ("status",)),
            "rows": reg.counter(
                "ltpu_serve_rows_total",
                "rows admitted into terminal requests", ("status",)),
            "latency": reg.histogram(
                "ltpu_serve_latency_ms",
                "total request latency (ok requests)",
                buckets=lat_buckets),
            "occupancy": reg.histogram(
                "ltpu_serve_batch_occupancy",
                "dispatch-batch fill fraction",
                buckets=_obs_metrics.OCCUPANCY_BUCKETS),
            "swaps": reg.counter(
                "ltpu_serve_swaps_total", "model hot-swaps"),
            # the explanation lane gets its own request/row/latency
            # series: explain latency is a different distribution
            # (O(depth^2) per leaf) and blending it into the predict
            # histogram would poison the rollback watchdog's p99
            "ex_requests": reg.counter(
                "ltpu_serve_explain_requests_total",
                "explain requests by terminal status", ("status",)),
            "ex_rows": reg.counter(
                "ltpu_serve_explain_rows_total",
                "rows admitted into terminal explain requests",
                ("status",)),
            "ex_latency": reg.histogram(
                "ltpu_serve_explain_latency_ms",
                "total explain request latency (ok requests)",
                buckets=lat_buckets),
            "fp_batches": reg.counter(
                "ltpu_serve_fastpath_batches_total",
                "predict batches dispatched on the single-row "
                "fast path"),
            "fp_rows": reg.counter(
                "ltpu_serve_fastpath_rows_total",
                "rows dispatched on the single-row fast path"),
        }
        # request-path fast lane: labeled children resolved once, not
        # per request (the registry lookup costs real microseconds at
        # serve rates)
        m["lat_child"] = m["latency"].labels()
        m["ex_lat_child"] = m["ex_latency"].labels()
        m["occ_child"] = m["occupancy"].labels()
        m["req_children"] = {}
        m["ex_req_children"] = {}
        # gauges capture self: remember the closures so stop() can
        # release them (a dead server must not stay pinned in the
        # process-global registry through its scrape callbacks)
        m["gauges"] = {
            "ltpu_serve_queue_requests":
                ("admitted requests pending dispatch",
                 lambda: self.queue.depth()[0]),
            "ltpu_serve_queue_rows":
                ("admitted rows pending dispatch",
                 lambda: self.queue.depth()[1]),
            "ltpu_serve_draining":
                ("1 once a graceful drain began",
                 lambda: 1.0 if self.draining else 0.0),
            "ltpu_serve_model_version":
                ("active published model version",
                 lambda: float(self.version() or 0)),
        }
        for name, (help_, fn) in m["gauges"].items():
            reg.gauge_callback(name, fn, help_)
        return m

    def _metric_children(self, status: str, kind: str = "predict"):
        key = "ex_req_children" if kind == "explain" \
            else "req_children"
        ch = self._metrics[key].get(status)
        if ch is None:                     # benign race: idempotent
            base = ("ex_requests", "ex_rows") if kind == "explain" \
                else ("requests", "rows")
            ch = (self._metrics[base[0]].labels(status=status),
                  self._metrics[base[1]].labels(status=status))
            self._metrics[key][status] = ch
        return ch

    def _make_recorder(self, telemetry):
        from ..utils import telemetry as _t
        if telemetry is not None:
            return telemetry                     # caller-owned recorder
        if not self.config.telemetry_file:
            return None
        info: Dict[str, Any] = {"task": "serve"}
        try:
            import jax
            info["backend"] = jax.default_backend()
        except Exception:
            info["backend"] = "unknown"
        return _t.RunRecorder(self.config.telemetry_file, run_info=info)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "Server":
        if self._threads:
            return self
        self._stop.clear()
        for i in range(self.config.workers):
            t = threading.Thread(target=self._worker,
                                 name=f"ltpu-serve-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Stop admissions, drain pending work, join the dispatchers,
        flush telemetry.  Idempotent."""
        self.queue.close()
        for t in self._threads:
            t.join(timeout)
        self._threads = []
        self._stop.set()
        # anything a dead worker left behind fails loudly, not silently
        while True:
            leftovers, _ = self.queue.drain_batch(1 << 30, 0.0,
                                                  self._stop)
            if not leftovers:
                break
            for r in leftovers:
                if r.finish("error", error="server stopped"):
                    self._emit(r)
        if self._metrics is not None:
            reg = _obs_metrics.get_registry()
            for name, (_help, fn) in self._metrics["gauges"].items():
                reg.release_gauge_callback(name, fn)
        if self._owns_recorder and self._recorder is not None:
            self._recorder.close()
            self._recorder = None

    def drain(self, grace_s: Optional[float] = None) -> None:
        """Graceful drain: stop admitting (the HTTP front answers 503
        + Retry-After while ``draining`` is set), finish every
        already-admitted request, then stop.  This is what a SIGTERM
        triggers, so supervisor-driven restarts never drop admitted
        work.  Idempotent."""
        self.draining = True
        grace = self.config.drain_grace_s if grace_s is None \
            else float(grace_s)
        self.queue.close()                 # new submits raise ServerClosed
        deadline = time.monotonic() + max(grace, 0.0)
        while self.queue.depth()[0] > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        self.stop(timeout=max(deadline - time.monotonic(), 0.1))

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- model management ------------------------------------------------
    @property
    def registry(self) -> ModelRegistry:
        """The default tenant's registry (the single-model API)."""
        return self._registries[DEFAULT_MODEL]

    def registry_for(self, model: Optional[str],
                     create: bool = False) -> ModelRegistry:
        """The named tenant's registry.  ``create=True`` (the swap
        path) opens the tenancy seam: publishing to a new name creates
        its registry; the request path NEVER creates one — an unknown
        name raises :class:`UnknownModel` (HTTP 404)."""
        name = model or DEFAULT_MODEL
        with self._registries_lock:
            reg = self._registries.get(name)
            if reg is None:
                if not create:
                    raise UnknownModel(
                        f"no model {name!r} published (known: "
                        f"{sorted(self._registries)})")
                reg = ModelRegistry(
                    chunk_rows=self.config.max_batch_rows,
                    warm=self.config.warmup,
                    fastpath_rows=self.config.fastpath_max_rows)
                self._registries[name] = reg
        return reg

    def models(self) -> Dict[str, Optional[str]]:
        """{model name: active fingerprint} across tenants (the
        ``/healthz`` body's ``models`` map — what the fleet
        supervisor's reconciler and the router's scrape read)."""
        with self._registries_lock:
            regs = dict(self._registries)
        out: Dict[str, Optional[str]] = {}
        for name, reg in regs.items():
            ver = reg.current()
            out[name] = ver.model_id if ver is not None else None
        return out

    def swap(self, booster=None, model_file: Optional[str] = None,
             model_str: Optional[str] = None,
             model: Optional[str] = None) -> int:
        """Publish a new model version (flatten + pre-warm + atomic
        swap) to the named tenant (default when ``model`` is None).
        In-flight requests complete against their admitted version;
        only new admissions see the new one."""
        t0 = time.monotonic()
        name = model or DEFAULT_MODEL
        with self._registries_lock:
            created = name not in self._registries
        reg = self.registry_for(model, create=True)
        try:
            with _spans.span("swap", recorder=self._recorder) as sp:
                ver = reg.publish(booster=booster,
                                  model_file=model_file,
                                  model_str=model_str)
                sp.set(version=ver.version, model_id=ver.model_id,
                       model=name)
                # the publish trace rides the version: the FIRST
                # request this version serves emits a joined marker
                # span, closing the daemon->checkpoint->publish->
                # served-request loop
                ver.publish_trace = _spans.current()
        except BaseException:
            # a failed FIRST publish to a new name must not leave an
            # empty tenant behind: it would answer 500 (no model
            # published) instead of the documented 404 unknown_model
            # and pollute the /healthz models map
            if created:
                with self._registries_lock:
                    cur = self._registries.get(name)
                    if cur is reg and reg.current() is None:
                        del self._registries[name]
            raise
        if self._metrics is not None:
            self._metrics["swaps"].inc()
        if self._recorder is not None:
            self._recorder.emit(
                "serve", status="swap", rows=0,
                total_ms=round((time.monotonic() - t0) * 1e3, 3),
                version=ver.version, model_id=ver.model_id,
                model=model or DEFAULT_MODEL,
                warmup=ver.warmup_info)
        return ver.version

    def version(self) -> Optional[int]:
        ver = self.registry.current()
        return ver.version if ver is not None else None

    # -- client surface --------------------------------------------------
    def submit(self, data, priority: int = 0,
               timeout_ms: Optional[float] = None,
               raw: bool = False,
               model: Optional[str] = None,
               kind: str = "predict") -> Request:
        """Admit one request against the named tenant (default when
        ``model`` is None); returns the request future (``.value()``
        blocks for the result or raises).  ``kind="explain"`` admits
        into the explanation lane (per-row SHAP contributions; the
        batcher never mixes lanes in one device batch).  Raises
        :class:`QueueSaturated` immediately on backpressure and
        :class:`UnknownModel` for an unpublished tenant name."""
        if kind not in ("predict", "explain"):
            raise ValueError(f"unknown request kind {kind!r}")
        if not self._threads:
            raise ServerClosed("server not started (call start())")
        ver = self.registry_for(model).require()
        X = np.ascontiguousarray(np.asarray(data, np.float64))
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[0] == 0:
            raise ValueError(f"expected a non-empty 2-D matrix, got "
                             f"shape {X.shape}")
        if X.shape[1] < ver.requires_features:
            raise ValueError(
                f"input has {X.shape[1]} features but model v"
                f"{ver.version} references feature "
                f"{ver.requires_features - 1}")
        if X.shape[1] != ver.num_features:
            # width-normalize so requests concatenate into one batch;
            # extra columns are ignored exactly as the engine would
            fixed = np.zeros((X.shape[0], ver.num_features))
            w = min(X.shape[1], ver.num_features)
            fixed[:, :w] = X[:, :w]
            X = fixed
        tmo = self.config.timeout_ms if timeout_ms is None \
            else float(timeout_ms)
        deadline = time.monotonic() + tmo / 1e3 if tmo > 0 else None
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        req = Request(rid, X, raw, priority, deadline, ver, kind=kind)
        # the serve record is emitted on a dispatcher thread; carry
        # the submitter's trace context (HTTP header / caller span)
        # on the request so the record still joins its trace
        req.trace = _spans.current()
        try:
            shed = self.queue.admit(req)
        except QueueSaturated as exc:
            req.finish("rejected", error=str(exc),
                       retry_after_ms=exc.retry_after_ms)
            self._emit(req)
            raise
        for v in shed:
            self._emit(v)
        return req

    def predict(self, data, priority: int = 0,
                timeout_ms: Optional[float] = None,
                raw: bool = False,
                model: Optional[str] = None) -> np.ndarray:
        """Blocking predict through the micro-batching scheduler.
        Output matches ``Booster.predict`` (``raw=True`` matches
        ``raw_score=True``)."""
        req = self.submit(data, priority=priority,
                          timeout_ms=timeout_ms, raw=raw, model=model)
        return self._await(req)

    def explain(self, data, priority: int = 0,
                timeout_ms: Optional[float] = None,
                model: Optional[str] = None) -> np.ndarray:
        """Blocking per-row SHAP contributions through the explanation
        lane.  Output matches ``Booster.predict(pred_contrib=True)``:
        (rows, nf+1) with the bias in the last column, multiclass
        flattened to (rows, k*(nf+1)).  Contributions are raw-score
        space by definition (per row, sum + bias == predict_raw)."""
        req = self.submit(data, priority=priority,
                          timeout_ms=timeout_ms, raw=True, model=model,
                          kind="explain")
        return self._await(req)

    def _await(self, req: Request) -> np.ndarray:
        # grace beyond the deadline: the dispatcher times the request
        # out itself; this guard only catches a wedged worker
        grace = None
        if req.deadline is not None:
            grace = max(req.deadline - time.monotonic(), 0.0) + 60.0
        if not req.wait(grace):
            # finish() is first-writer-wins: if the dispatcher beat us
            # between wait() and here, this is a no-op and no second
            # telemetry record is emitted
            if req.finish("error", error="dispatcher stalled"):
                self._emit(req)
        return req.value()

    # -- dispatcher ------------------------------------------------------
    def _worker(self) -> None:
        while True:
            batch, timed = self.batcher.next_batch(self._stop)
            for t in timed:
                self._emit(t)
            if batch is None:
                if (self._stop.is_set() or self.queue.closed()) \
                        and self.queue.depth()[0] == 0:
                    return
                continue
            self._dispatch(batch)

    def _dispatch(self, batch: Batch) -> None:
        from ..utils.telemetry import counters_snapshot
        t0 = time.monotonic()
        explain = batch.kind == "explain"
        compiles = 0.0
        try:
            # fault-injection points (utils/faults.py):
            # ``serve.dispatch`` covers every batch, ``serve.explain``
            # only the explanation lane — "error" exercises the real
            # failure path below; "sleep_<ms>" degrades latency so the
            # rollback controller's p99 trigger is testable without a
            # genuinely slow model
            mode = _faults.fire("serve.explain") if explain \
                else _faults.fire("serve.dispatch")
            if explain and not mode:
                mode = _faults.fire("serve.dispatch")
            if mode.startswith("sleep_"):
                time.sleep(max(float(mode.split("_", 1)[1]), 0.0) / 1e3)
            elif mode == "error":
                raise RuntimeError(
                    f"injected fault "
                    f"(serve.{'explain' if explain else 'dispatch'}"
                    f":error)")
            if explain:
                # steady-state explains must re-run cached programs;
                # the compile delta rides the explain record so
                # obs/rules.py can flag a warmed lane that compiles
                base = counters_snapshot().get("xla_compiles", 0.0)
                raw = batch.version.explain_batch(batch.X)
                compiles = counters_snapshot().get(
                    "xla_compiles", 0.0) - base
            elif batch.fastpath:
                raw = batch.version.predict_raw_fast_batch(batch.X)
            else:
                raw = batch.version.predict_raw_batch(batch.X)
        except Exception as exc:  # batch fails as a unit, loudly
            Log.warning("serve: batch dispatch failed: %s", exc)
            for r in batch.requests:
                r.timings["dispatch_ms"] = \
                    round((time.monotonic() - t0) * 1e3, 3)
                if r.finish("error", error=f"dispatch failed: {exc}"):
                    self._emit(r, batch)
            return
        dispatch_ms = round((time.monotonic() - t0) * 1e3, 3)
        # EWMA service-time hint drives the retry-after backpressure
        self.queue.service_ms_hint = round(
            0.8 * self.queue.service_ms_hint + 0.2 * dispatch_ms, 3)
        pos = 0
        for r in batch.requests:
            sl = raw[pos:pos + r.rows]
            pos += r.rows
            # contributions are raw-score space by definition (their
            # row sum reproduces predict_raw) — never converted
            out = sl if (r.raw or explain) \
                else batch.version.convert(sl)
            r.timings["dispatch_ms"] = dispatch_ms
            if r.finish("ok", result=out):
                self._emit(r, batch, compiles=compiles)
        _tele_counters.incr("serve_batches")
        _tele_counters.incr("serve_batch_rows", batch.rows)
        _tele_counters.incr("serve_padded_rows", batch.bucket_rows)
        if explain:
            _tele_counters.incr("serve_explain_batches")
            _tele_counters.incr("serve_explain_rows", batch.rows)
        elif batch.fastpath:
            _tele_counters.incr("serve_fastpath_batches")
            _tele_counters.incr("serve_fastpath_rows", batch.rows)
            if self._metrics is not None:
                self._metrics["fp_batches"].inc()
                self._metrics["fp_rows"].inc(batch.rows)

    # -- telemetry / stats -----------------------------------------------
    def _emit(self, req: Request, batch: Optional[Batch] = None,
              compiles: float = 0.0) -> None:
        status = req.status
        explain = req.kind == "explain"
        _tele_counters.incr("serve_requests")
        if explain:
            _tele_counters.incr("serve_explain_requests")
        if status != "ok":
            _tele_counters.incr(f"serve_{status}")
        with self._counts_lock:
            self._counts[status] = self._counts.get(status, 0) + 1
        if status == "ok":
            self._lat_hist.observe(req.timings.get("total_ms", 0.0))
        if self._metrics is not None:
            c_req, c_rows = self._metric_children(status, req.kind)
            c_req.inc()
            c_rows.inc(req.rows)
            if status == "ok":
                self._metrics["ex_lat_child" if explain
                              else "lat_child"].observe(
                    req.timings.get("total_ms", 0.0))
                if batch is not None:
                    self._metrics["occ_child"].observe(
                        batch.occupancy)
        ver = req.version
        pub_trace = getattr(ver, "publish_trace", None) if ver else None
        if status == "ok" and pub_trace is not None:
            # first served request of a freshly published version:
            # emit one marker span joined to the publish trace
            with self._counts_lock:
                pub_trace, ver.publish_trace = ver.publish_trace, None
            if pub_trace is not None:
                _spans.point("first_request", pub_trace,
                             recorder=self._recorder,
                             version=ver.version, model_id=ver.model_id,
                             rows=req.rows,
                             total_ms=round(
                                 req.timings.get("total_ms", 0.0), 3))
        if self._recorder is None:
            return
        fields: Dict[str, Any] = {
            "status": status, "rows": req.rows,
            "total_ms": round(req.timings.get("total_ms", 0.0), 3),
            "priority": req.priority,
        }
        for key in ("queue_ms", "assemble_ms", "dispatch_ms"):
            if key in req.timings:
                fields[key] = req.timings[key]
        if req.version is not None:
            fields["version"] = req.version.version
            fields["model_id"] = req.version.model_id
        if req.trace is not None:
            fields["trace_id"], fields["span_id"] = req.trace
        if batch is not None:
            fields["batch_rows"] = batch.rows
            fields["bucket_rows"] = batch.bucket_rows
            fields["occupancy"] = round(batch.occupancy, 4)
            if batch.fastpath:
                fields["fastpath"] = True
        if explain:
            # rides the record so obs/rules.py can flag a warmed
            # explain lane that still compiles (explain_compile MED);
            # 0 past warmup IS the contract, so it is always present
            fields["xla_compiles"] = compiles
        if req.error and status not in ("ok",):
            fields["error"] = str(req.error)[:200]
        self._recorder.emit("explain" if explain else "serve",
                            **fields)

    def stats(self) -> Dict[str, Any]:
        from ..ops.predict import get_engine
        from ..ops.shap import get_shap_engine
        with self._counts_lock:
            counts = dict(self._counts)
        depth_reqs, depth_rows = self.queue.depth()
        ver = self.registry.current()
        return {
            "version": ver.version if ver else None,
            "model_id": ver.model_id if ver else None,
            "models": self.models(),
            "draining": self.draining,
            "queue_requests": depth_reqs,
            "queue_rows": depth_rows,
            "requests": counts,
            # interpolated from the bounded histogram (O(1) memory
            # and no per-scrape sort, whatever the request count)
            "latency_ms": {
                "p50": round(self._lat_hist.percentile(0.50), 3),
                "p95": round(self._lat_hist.percentile(0.95), 3),
                "p99": round(self._lat_hist.percentile(0.99), 3),
            },
            "retry_after_ms": self.queue.retry_after_ms(),
            "engine_cache": get_engine().cache_info(),
            "explain_cache": get_shap_engine().cache_info(),
            "versions": self.registry.history(),
        }

    def metrics_text(self) -> str:
        """The Prometheus exposition ``GET /metrics`` serves (the
        process-wide registry: this server's series plus every
        mirrored telemetry counter)."""
        return _obs_metrics.render()
