"""Stdlib threaded JSON endpoint over :class:`~.server.Server`.

No framework dependency — ``http.server.ThreadingHTTPServer`` with one
handler thread per connection blocking on the request future, which is
exactly the shape the micro-batcher wants (many concurrent submitters
to coalesce).  Routes:

- ``POST /predict``  ``{"rows": [[...], ...], "raw": false,
  "priority": 0, "timeout_ms": 500}`` ->
  ``{"predictions": [...], "version": v, "total_ms": t}``;
  429 + ``Retry-After`` on backpressure, 503 on shed, 504 on timeout.
- ``POST /swap``     ``{"model_file": path}`` or ``{"model_str": s}``
  -> ``{"version": v}`` (blocks through flatten + pre-warm; in-flight
  requests finish on their admitted version).
- ``GET /healthz``   liveness + active version.
- ``GET /stats``     queue depth, latency percentiles, engine cache.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..utils.log import Log
from .admission import (QueueSaturated, RequestShed, RequestTimeout,
                        ServeError, ServerClosed)
from .server import Server


def _json_handler_for(server: Server):
    class ServeHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # -- plumbing --------------------------------------------------
        def _send(self, code: int, obj: Dict[str, Any],
                  headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> Optional[Dict[str, Any]]:
            try:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, TypeError):
                return None

        def log_message(self, fmt, *args):  # route through our logger
            Log.debug("serve http: " + fmt, *args)

        # -- routes ----------------------------------------------------
        def do_GET(self):
            if self.path == "/healthz":
                depth_reqs, depth_rows = server.queue.depth()
                self._send(200, {"ok": True,
                                 "version": server.version(),
                                 "queue_requests": depth_reqs,
                                 "queue_rows": depth_rows})
            elif self.path == "/stats":
                self._send(200, server.stats())
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path == "/predict":
                self._predict()
            elif self.path == "/swap":
                self._swap()
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def _predict(self):
            body = self._read_json()
            if body is None or "rows" not in body:
                self._send(400, {"error": "body must be JSON with "
                                          "a 'rows' matrix"})
                return
            try:
                X = np.asarray(body["rows"], np.float64)
            except (ValueError, TypeError) as exc:
                self._send(400, {"error": f"bad rows: {exc}"})
                return
            try:
                req = server.submit(
                    X, priority=int(body.get("priority", 0)),
                    timeout_ms=body.get("timeout_ms"),
                    raw=bool(body.get("raw", False)))
                out = req.value()
            except QueueSaturated as exc:
                # RFC 7231 Retry-After is integer seconds; the precise
                # hint rides in the JSON retry_after_ms field
                retry_s = max(int(-(-exc.retry_after_ms // 1e3)), 1)
                self._send(429, {"error": str(exc),
                                 "retry_after_ms": exc.retry_after_ms},
                           headers={"Retry-After": str(retry_s)})
                return
            except RequestTimeout as exc:
                self._send(504, {"error": str(exc)})
                return
            except (RequestShed, ServerClosed) as exc:
                self._send(503, {"error": str(exc)})
                return
            except ValueError as exc:      # malformed input: client fault
                self._send(400, {"error": str(exc)})
                return
            except ServeError as exc:      # dispatch failed: server fault
                self._send(500, {"error": str(exc)})
                return
            self._send(200, {
                "predictions": np.asarray(out).tolist(),
                "version": req.version.version,
                "total_ms": round(req.timings.get("total_ms", 0.0), 3)})

        def _swap(self):
            body = self._read_json()
            if body is None or not (body.get("model_file") or
                                    body.get("model_str")):
                self._send(400, {"error": "body must carry model_file "
                                          "or model_str"})
                return
            try:
                v = server.swap(model_file=body.get("model_file"),
                                model_str=body.get("model_str"))
            except Exception as exc:
                self._send(400, {"error": f"swap failed: {exc}"})
                return
            self._send(200, {"version": v})

    return ServeHandler


def make_http_server(server: Server, host: Optional[str] = None,
                     port: Optional[int] = None) -> ThreadingHTTPServer:
    """Bind (not yet serving) — call ``serve_forever()`` or use
    :func:`serve_http`.  ``port=0`` binds an ephemeral port."""
    host = server.config.host if host is None else host
    port = server.config.port if port is None else port
    httpd = ThreadingHTTPServer((host, port), _json_handler_for(server))
    httpd.daemon_threads = True
    return httpd


def serve_http(server: Server, host: Optional[str] = None,
               port: Optional[int] = None,
               background: bool = False
               ) -> Tuple[ThreadingHTTPServer, Optional[threading.Thread]]:
    """Start the Server's dispatchers and the HTTP front.  With
    ``background=True`` the accept loop runs in a daemon thread and
    the pair ``(httpd, thread)`` returns immediately (the test /
    loadgen mode); otherwise this blocks until interrupted."""
    server.start()
    httpd = make_http_server(server, host, port)
    Log.info("serve: listening on http://%s:%d (model v%s)",
             *httpd.server_address[:2], server.version())
    if background:
        t = threading.Thread(target=httpd.serve_forever,
                             name="ltpu-serve-http", daemon=True)
        t.start()
        return httpd, t
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        Log.info("serve: interrupted, draining")
    finally:
        httpd.shutdown()
        server.stop()
    return httpd, None
