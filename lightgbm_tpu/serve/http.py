"""Stdlib threaded JSON endpoint over :class:`~.server.Server`.

No framework dependency — ``http.server.ThreadingHTTPServer`` with one
handler thread per connection blocking on the request future, which is
exactly the shape the micro-batcher wants (many concurrent submitters
to coalesce).  Routes:

- ``POST /predict``  ``{"rows": [[...], ...], "raw": false,
  "priority": 0, "timeout_ms": 500}`` ->
  ``{"predictions": [...], "version": v, "model_id": id,
  "total_ms": t}``; 429 + ``Retry-After`` on backpressure, 503 on
  shed/drain, 504 on timeout.  Errors are STRUCTURED: every non-200
  body is ``{"error": msg, "code": slug}`` — malformed JSON, wrong
  dtypes and oversized bodies map to 400/413, never to a 500
  traceback.
- ``POST /explain``  same body shape as ``/predict`` (``raw`` is
  ignored — contributions are raw-score space by definition) ->
  ``{"contributions": [[...], ...], ...}``: per-row SHAP values in
  the ``Booster.predict(pred_contrib=True)`` layout, served by the
  device explanation engine (``ops/shap.py``) through its own
  micro-batch lane (predict and explain never mix in one device
  batch).
- ``POST /swap``     ``{"model_file": path}`` or ``{"model_str": s}``
  -> ``{"version": v, "model_id": id}`` (blocks through flatten +
  pre-warm; in-flight requests finish on their admitted version).
- ``POST /v1/<model>/predict`` / ``/v1/<model>/explain`` /
  ``POST /v1/<model>/swap``
  multi-model tenancy: the named tenant's registry (created on first
  swap) — one replica serves many boosters, tenants never mixing in a
  device batch (requests pin their version at admission).  An
  unpublished name answers a structured 404 ``unknown_model``; the
  bare routes alias the ``default`` tenant.
- ``GET /healthz``   liveness + active version/model_id; 503 with
  ``{"draining": true}`` once a drain begins, so supervisors and load
  balancers stop routing to a replica that is going away.
- ``GET /stats``     queue depth, latency percentiles, engine cache.
- ``GET /metrics``   Prometheus text exposition: live request
  counters by status, bounded latency/occupancy histograms, queue
  gauges, mirrored telemetry counters (``obs/metrics.py``;
  ``serve_metrics=false`` hides the route).  ``POST`` routes honor an
  ``X-Ltpu-Trace`` carrier header (``obs/spans.py``), so a fleet
  publish's ``/swap`` — and the records it causes — join the
  publishing trace.
- ``GET /model``     the active version's reference-format model text
  (the watcher's rollback-baseline capture).
- ``POST/GET /faults``  remote driving surface of the fault-injection
  registry (``utils/faults.py``) — only with
  ``serve_debug_faults=true``, 403 otherwise.

Graceful drain: :func:`serve_http` in foreground mode installs
SIGTERM/SIGINT handlers that run admit-stop -> finish-admitted ->
exit (``Server.drain``), so a supervisor-driven restart never drops a
request the queue already accepted.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..obs import spans as _spans
from ..utils import faults as _faults
from ..utils.log import Log
from .admission import (QueueSaturated, RequestShed, RequestTimeout,
                        ServeError, ServerClosed, UnknownModel)
from .server import Server


def split_model_route(path: str):
    """Parse a tenancy route ``/v1/<model>/<verb>`` into
    ``(model, "/<verb>")``; any other path returns ``(None, path)``
    (un-prefixed routes act on the default tenant).  Shared with the
    router front (``serve/router.py``), so both tiers agree on the
    URL shape."""
    if path.startswith("/v1/"):
        parts = path.split("/")
        # ["", "v1", "<model>", "<verb>"]
        if len(parts) == 4 and parts[2] and parts[3]:
            return parts[2], "/" + parts[3]
    return None, path


class _BadRequest(Exception):
    """Client fault mapped to a structured 400/413 response."""

    def __init__(self, code: int, slug: str, msg: str):
        super().__init__(msg)
        self.http_code = int(code)
        self.slug = str(slug)


def _json_handler_for(server: Server):
    class ServeHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # -- plumbing --------------------------------------------------
        def _send(self, code: int, obj: Dict[str, Any],
                  headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str,
                       content_type: str = "text/plain; "
                                           "version=0.0.4") -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> Dict[str, Any]:
            """Parse the request body, hardened: a bounded read and
            structured failures — an abusive payload must cost one
            cheap rejection, not memory or a traceback."""
            try:
                n = int(self.headers.get("Content-Length", 0))
            except (TypeError, ValueError):
                raise _BadRequest(400, "bad_content_length",
                                  "Content-Length is not an integer")
            if n < 0:
                raise _BadRequest(400, "bad_content_length",
                                  "negative Content-Length")
            if n > server.config.max_body_bytes:
                raise _BadRequest(
                    413, "body_too_large",
                    f"request body {n} bytes exceeds "
                    f"serve_max_body_bytes="
                    f"{server.config.max_body_bytes}")
            try:
                raw = self.rfile.read(n) if n else b"{}"
            except OSError as exc:
                raise _BadRequest(400, "body_read_failed",
                                  f"could not read body: {exc}")
            try:
                obj = json.loads(raw or b"{}")
            except ValueError as exc:
                raise _BadRequest(400, "bad_json",
                                  f"body is not valid JSON: {exc}")
            if not isinstance(obj, dict):
                raise _BadRequest(400, "bad_json",
                                  "body must be a JSON object")
            return obj

        def _drain_reject(self) -> bool:
            """503 + Retry-After for new work once draining began."""
            if not server.draining:
                return False
            self._send(503, {"error": "server is draining",
                             "code": "draining",
                             "draining": True},
                       headers={"Retry-After": "1"})
            return True

        def log_message(self, fmt, *args):  # route through our logger
            Log.debug("serve http: " + fmt, *args)

        def _guarded(self, fn) -> None:
            """Route wrapper: client faults -> structured 4xx, anything
            unexpected -> structured 500 (never a traceback into the
            socket)."""
            try:
                fn()
            except _BadRequest as exc:
                # the body may be unread (413 / bad Content-Length):
                # close, or the keep-alive stream would parse the
                # leftover body bytes as the next request line
                self.close_connection = True
                self._send(exc.http_code, {"error": str(exc),
                                           "code": exc.slug})
            except (BrokenPipeError, ConnectionResetError):
                pass                      # client went away mid-response
            except Exception as exc:      # noqa: BLE001 - last resort
                Log.warning("serve http: unhandled %s: %s",
                            type(exc).__name__, exc)
                try:
                    self._send(500, {"error": f"internal error: {exc}",
                                     "code": "internal"})
                except Exception:         # noqa: BLE001 - socket dead
                    pass

        # -- routes ----------------------------------------------------
        def do_GET(self):
            self._guarded(self._get)

        def do_POST(self):
            self._guarded(self._post)

        def _get(self):
            if self.path == "/healthz":
                depth_reqs, depth_rows = server.queue.depth()
                ver = server.registry.current()
                body = {"ok": not server.draining,
                        "draining": server.draining,
                        "version": ver.version if ver else None,
                        "model_id": ver.model_id if ver else None,
                        # per-tenant fingerprints: the supervisor's
                        # reconciler and the router's scrape read this
                        # to spot stale-model replicas mid-deploy
                        "models": server.models(),
                        "queue_requests": depth_reqs,
                        "queue_rows": depth_rows}
                self._send(503 if server.draining else 200, body)
            elif self.path == "/stats":
                self._send(200, server.stats())
            elif self.path == "/metrics":
                if not server.config.metrics:
                    self._send(404, {"error": "serve_metrics is off",
                                     "code": "no_route"})
                else:
                    # Prometheus text exposition: live counters by
                    # status, bounded latency/occupancy histograms,
                    # queue gauges, mirrored telemetry counters —
                    # FleetSupervisor.metrics_text aggregates these
                    # per replica (docs/Observability.md)
                    self._send_text(200, server.metrics_text())
            elif self.path == "/model":
                ver = server.registry.current()
                if ver is None:
                    self._send(404, {"error": "no model published",
                                     "code": "no_model"})
                else:
                    self._send(200, {"version": ver.version,
                                     "model_id": ver.model_id,
                                     "model_str": ver.model_text})
            elif self.path == "/faults":
                if not server.config.debug_faults:
                    self._send(403, {"error": "serve_debug_faults is "
                                              "off", "code": "forbidden"})
                else:
                    self._send(200, _faults.snapshot())
            else:
                # tenancy route: /v1/<model>/model reads the named
                # registry (the fleet target's named-tenant
                # active_model probe)
                model, verb = split_model_route(self.path)
                if model is not None and verb == "/model":
                    try:
                        ver = server.registry_for(model).current()
                    except UnknownModel as exc:
                        self._send(404, {"error": str(exc),
                                         "code": "unknown_model"})
                        return
                    if ver is None:
                        self._send(404, {"error": "no model published",
                                         "code": "no_model"})
                    else:
                        self._send(200, {"version": ver.version,
                                         "model_id": ver.model_id,
                                         "model_str": ver.model_text})
                    return
                self._send(404, {"error": f"no route {self.path}",
                                 "code": "no_route"})

        def _post(self):
            # trace propagation (obs/spans.py): an X-Ltpu-Trace
            # carrier makes this request's records join the sender's
            # trace — the fleet's /swap carries the publish trace, a
            # client may carry its own onto /predict
            with _spans.use(_spans.from_headers(self.headers)):
                # tenancy routes: /v1/<model>/predict|swap act on the
                # named registry; bare routes on the default tenant
                model, verb = split_model_route(self.path)
                if verb == "/predict":
                    self._predict(model)
                elif verb == "/explain":
                    self._predict(model, kind="explain")
                elif verb == "/swap":
                    self._swap(model)
                elif self.path == "/faults":
                    self._faults()
                else:
                    self._send(404, {"error": f"no route {self.path}",
                                     "code": "no_route"})

        def _predict(self, model=None, kind="predict"):
            # one handler, two lanes: /explain shares the whole
            # admission/backpressure/error surface and differs only in
            # the submit kind and the response key.
            # fault-injection point ``http.request``: "error" answers
            # a structured 500; "drop" closes the connection with no
            # response (a client-visible transport failure)
            mode = _faults.fire("http.request")
            if mode == "error":
                self._send(500, {"error": "injected fault "
                                          "(http.request:error)",
                                 "code": "injected"})
                return
            if mode == "drop":
                self.close_connection = True
                return
            if self._drain_reject():
                return
            body = self._read_json()
            if "rows" not in body:
                raise _BadRequest(400, "missing_rows",
                                  "body must carry a 'rows' matrix")
            try:
                X = np.asarray(body["rows"], np.float64)
            except (ValueError, TypeError) as exc:
                raise _BadRequest(400, "bad_rows",
                                  f"'rows' is not a numeric matrix: "
                                  f"{exc}")
            try:
                priority = int(body.get("priority", 0))
                timeout_ms = body.get("timeout_ms")
                if timeout_ms is not None:
                    timeout_ms = float(timeout_ms)
                raw = bool(body.get("raw", False))
            except (ValueError, TypeError) as exc:
                raise _BadRequest(400, "bad_field",
                                  f"priority/timeout_ms malformed: "
                                  f"{exc}")
            try:
                req = server.submit(X, priority=priority,
                                    timeout_ms=timeout_ms,
                                    raw=raw or kind == "explain",
                                    model=model, kind=kind)
                out = req.value()
            except UnknownModel as exc:
                # tenancy 404: the name is not in this replica's
                # routing table (vs 429 budget / 503 shed-or-drain)
                self._send(404, {"error": str(exc),
                                 "code": "unknown_model"})
                return
            except QueueSaturated as exc:
                # RFC 7231 Retry-After is integer seconds; the precise
                # hint rides in the JSON retry_after_ms field
                retry_s = max(int(-(-exc.retry_after_ms // 1e3)), 1)
                self._send(429, {"error": str(exc),
                                 "code": "backpressure",
                                 "retry_after_ms": exc.retry_after_ms},
                           headers={"Retry-After": str(retry_s)})
                return
            except RequestTimeout as exc:
                self._send(504, {"error": str(exc), "code": "timeout"})
                return
            except (RequestShed, ServerClosed) as exc:
                self._send(503, {"error": str(exc), "code": "shed"},
                           headers={"Retry-After": "1"})
                return
            except (ValueError, TypeError) as exc:  # malformed input
                raise _BadRequest(400, "bad_rows", str(exc))
            except ServeError as exc:      # dispatch failed: server fault
                self._send(500, {"error": str(exc), "code": "dispatch"})
                return
            key = "contributions" if kind == "explain" \
                else "predictions"
            self._send(200, {
                key: np.asarray(out).tolist(),
                "version": req.version.version,
                "model_id": req.version.model_id,
                "total_ms": round(req.timings.get("total_ms", 0.0), 3)})

        def _swap(self, model=None):
            if self._drain_reject():
                return
            body = self._read_json()
            if not (body.get("model_file") or body.get("model_str")):
                raise _BadRequest(400, "missing_model",
                                  "body must carry model_file or "
                                  "model_str")
            try:
                v = server.swap(model_file=body.get("model_file"),
                                model_str=body.get("model_str"),
                                model=model)
            except Exception as exc:      # noqa: BLE001 - client input
                self._send(400, {"error": f"swap failed: {exc}",
                                 "code": "swap_failed"})
                return
            ver = server.registry_for(model).current()
            self._send(200, {"version": v,
                             "model_id": ver.model_id if ver else None})

        def _faults(self):
            if not server.config.debug_faults:
                self._send(403, {"error": "serve_debug_faults is off",
                                 "code": "forbidden"})
                return
            body = self._read_json()
            spec = body.get("spec", "")
            try:
                parsed = _faults.configure(str(spec))
            except ValueError as exc:
                raise _BadRequest(400, "bad_spec", str(exc))
            if body.get("reset"):
                _faults.reset()
            self._send(200, {"ok": True,
                             "specs": [repr(s) for s in parsed],
                             "snapshot": _faults.snapshot()})

    return ServeHandler


def make_http_server(server: Server, host: Optional[str] = None,
                     port: Optional[int] = None) -> ThreadingHTTPServer:
    """Bind (not yet serving) — call ``serve_forever()`` or use
    :func:`serve_http`.  ``port=0`` binds an ephemeral port."""
    host = server.config.host if host is None else host
    port = server.config.port if port is None else port
    httpd = ThreadingHTTPServer((host, port), _json_handler_for(server))
    httpd.daemon_threads = True
    if server.config.port_file:
        # ephemeral-port discovery for the fleet supervisor: write to
        # a temp sibling + rename so a reader never sees a torn write
        tmp = server.config.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write("%d\n" % httpd.server_address[1])
        os.replace(tmp, server.config.port_file)
    return httpd


def serve_http(server: Server, host: Optional[str] = None,
               port: Optional[int] = None,
               background: bool = False
               ) -> Tuple[ThreadingHTTPServer, Optional[threading.Thread]]:
    """Start the Server's dispatchers and the HTTP front.  With
    ``background=True`` the accept loop runs in a daemon thread and
    the pair ``(httpd, thread)`` returns immediately (the test /
    loadgen / replica-handle mode); otherwise this blocks until a
    SIGTERM/SIGINT triggers the graceful drain: stop admitting (503 +
    Retry-After), finish admitted requests within
    ``serve_drain_grace_s``, then return."""
    server.start()
    httpd = make_http_server(server, host, port)
    Log.info("serve: listening on http://%s:%d (model v%s)",
             *httpd.server_address[:2], server.version())
    accept = threading.Thread(target=httpd.serve_forever,
                              name="ltpu-serve-http", daemon=True)
    accept.start()
    if background:
        return httpd, accept

    stop_evt = threading.Event()
    previous: Dict[int, Any] = {}

    def _on_signal(signum, frame):
        Log.info("serve: signal %d — draining (grace %.1fs)",
                 signum, server.config.drain_grace_s)
        stop_evt.set()

    installed = False
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGINT):
            previous[sig] = signal.signal(sig, _on_signal)
        installed = True
    try:
        stop_evt.wait()
    except KeyboardInterrupt:             # handlers not installed
        pass
    finally:
        try:
            server.drain()                # 503 new work, finish admitted
            # drained requests are complete; give their handler
            # threads a beat to serialize responses before the accept
            # loop (and likely the process) goes away
            time.sleep(0.2)
        finally:
            httpd.shutdown()
            httpd.server_close()
            if installed:
                for sig, old in previous.items():
                    signal.signal(sig, old)
    Log.info("serve: drained and stopped")
    return httpd, None
