"""Closed-loop fleet autoscaler: the controller the observability
plane was built for.

The control loop consumes the SLO engine's burn rates
(``obs/slo.py``) plus the live PR 13/14 gauges — in-flight occupancy,
routable backends, open circuit breakers, replica count — and acts on
two levers:

- **capacity**: grow/drain :class:`~.fleet.FleetSupervisor` replicas
  via :meth:`~.fleet.FleetSupervisor.scale_to`, bounded by
  ``autoscale_min_replicas`` / ``autoscale_max_replicas``;
- **admission**: when capacity cannot come up (already at max, or no
  supervisor attached), retune the router's per-model token buckets
  down to ``autoscale_shed_rows_per_s`` so cheap traffic sheds first
  (priority > 0 requests keep their overdraw reserve), restoring the
  original budgets once the burn clears.

It can never flap by construction: growing needs a page-grade signal
(fast burn above ``autoscale_grow_burn`` on BOTH fast windows, or
in-flight occupancy above ``autoscale_grow_queue``), draining needs
quiet — occupancy below ``autoscale_drain_util`` AND no burn —
**sustained** for ``autoscale_drain_idle_s``, and both directions hold
separate cooldowns (``autoscale_cooldown_s`` /
``autoscale_drain_cooldown_s``).

Every decision is a traced ``autoscale`` telemetry record carrying its
evidence inline (the inputs snapshot → the rule that fired → the
action taken), wrapped in an ``autoscale_decide`` span so
``trace_view.py`` joins controller decisions into the same timelines
as the requests they protect.  ``autoscale_dry_run`` computes and
emits identical decisions (``mode="dry_run"``) without touching the
fleet or the buckets.

Fault point ``autoscale.decide`` (``error`` | ``hang``) wedges the
controller deterministically; the chaos harness pins that a wedged
controller leaves the fleet serving at its current size.

Stdlib-only; importable without jax.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..obs import slo as _slo
from ..obs import spans as _spans
from ..utils import faults as _faults
from ..utils.log import Log

__all__ = ["Autoscaler"]


class Autoscaler:
    """The control loop; see the module docstring.  ``supervisor``
    and/or ``router`` may be None — without a supervisor only the
    admission lever is available, without a router only capacity."""

    def __init__(self, supervisor=None, router=None, slo=None,
                 config=None, recorder=None,
                 clock=time.monotonic):
        from .config import AutoscaleConfig
        if supervisor is None and router is None:
            raise ValueError("autoscaler needs a supervisor or a "
                             "router (it has no levers otherwise)")
        self.supervisor = supervisor
        self.router = router
        self.slo = slo
        self.config = config or AutoscaleConfig()
        self.config.validate()
        self.recorder = recorder
        self._clock = clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # hysteresis state (identical trajectory in dry-run, so
        # dry-run and active decisions match on the same inputs)
        self._last_grow_at = -float("inf")
        self._last_drain_at = -float("inf")
        self._idle_since: Optional[float] = None
        # model -> (original rows_per_s, original burst) while a shed
        # retune is active
        self._shed_saved: Dict[str, Any] = {}
        self.decisions = 0
        self.actions = 0

    # -- inputs --------------------------------------------------------
    def inputs(self) -> Dict[str, Any]:
        """One evidence snapshot: everything :meth:`decide` reads."""
        inp: Dict[str, Any] = {
            "replicas": 0, "routable": 0, "breakers_open": 0,
            "queue_frac": 0.0, "inflight": 0,
            "burn_fast": 0.0, "burn_mid": 0.0, "burn_slow": 0.0,
            "budget_remaining": 1.0, "shed_active":
                bool(self._shed_saved),
        }
        if self.supervisor is not None:
            slots = self.supervisor.slots()
            inp["replicas"] = len(slots)
            inp["routable"] = sum(1 for s in slots if s["in_rotation"])
        if self.router is not None:
            with self.router._lock:
                backends = list(self.router._backends.values())
                routes = list(self.router._routes.values())
            inp["breakers_open"] = sum(
                1 for b in backends if b.breaker.state == "open")
            inp["inflight"] = int(sum(r.inflight for r in routes))
            inp["queue_frac"] = round(
                _slo.router_queue_fraction(self.router), 4)
            if self.supervisor is None:
                inp["routable"] = sum(
                    1 for b in backends
                    if b.healthy and not b.draining)
        if self.slo is not None:
            for res in self.slo.snapshot().values():
                inp["burn_fast"] = max(inp["burn_fast"],
                                       res.get("burn_fast", 0.0))
                inp["burn_mid"] = max(inp["burn_mid"],
                                      res.get("burn_mid", 0.0))
                inp["burn_slow"] = max(inp["burn_slow"],
                                       res.get("burn_slow", 0.0))
                inp["budget_remaining"] = min(
                    inp["budget_remaining"],
                    res.get("budget_remaining", 1.0))
        return inp

    # -- the policy ----------------------------------------------------
    def decide(self, inp: Dict[str, Any], now: float
               ) -> List[Dict[str, Any]]:
        """Pure-policy step: inputs → decisions.  Mutates only the
        hysteresis clocks (cooldowns, idle timer) — never the fleet —
        so dry-run and active mode walk identical trajectories on
        identical inputs."""
        cfg = self.config
        out: List[Dict[str, Any]] = []
        replicas = int(inp.get("replicas", 0))
        burning = (inp["burn_fast"] > cfg.grow_burn and
                   inp["burn_mid"] > cfg.grow_burn)
        saturated = inp["queue_frac"] >= cfg.grow_queue
        grow_signal = burning or saturated
        rule = ("fast_burn" if burning else "queue_saturation") \
            if grow_signal else ""
        can_scale = self.supervisor is not None
        can_retune = self.router is not None

        if grow_signal:
            self._idle_since = None
            if can_scale and replicas < cfg.max_replicas and \
                    now - self._last_grow_at >= cfg.cooldown_s:
                self._last_grow_at = now
                out.append({"action": "grow", "rule": rule,
                            "from_replicas": replicas,
                            "to_replicas": replicas + 1})
            elif can_retune and not inp.get("shed_active"):
                # capacity can't come up (at max / cooling / no
                # supervisor): shed cheap traffic first
                out.append({"action": "retune_shed",
                            "rule": rule if (not can_scale or
                                             replicas >= cfg.max_replicas)
                            else f"{rule}_cooldown",
                            "rows_per_s": cfg.shed_rows_per_s})
        elif can_retune and \
                inp.get("budget_remaining", 1.0) < cfg.budget_floor and \
                not inp.get("shed_active"):
            self._idle_since = None
            out.append({"action": "retune_shed", "rule": "budget_floor",
                        "rows_per_s": cfg.shed_rows_per_s})
        else:
            if inp.get("shed_active") and \
                    inp["burn_fast"] <= cfg.grow_burn / 2 and \
                    not saturated and \
                    inp.get("budget_remaining", 1.0) >= \
                    cfg.budget_floor:
                # budget must ALSO be back above the floor, or restore
                # and the budget_floor retune would alternate forever
                out.append({"action": "retune_restore",
                            "rule": "burn_cleared"})
            quiet = (inp["queue_frac"] < cfg.drain_util and
                     inp["burn_fast"] <= cfg.grow_burn / 2)
            if quiet and can_scale and replicas > cfg.min_replicas:
                if self._idle_since is None:
                    self._idle_since = now
                elif (now - self._idle_since >= cfg.drain_idle_s and
                      now - self._last_drain_at >=
                      cfg.drain_cooldown_s):
                    self._last_drain_at = now
                    self._idle_since = now
                    out.append({"action": "drain", "rule": "idle",
                                "from_replicas": replicas,
                                "to_replicas": replicas - 1})
            elif not quiet:
                self._idle_since = None
        return out

    # -- actuation -----------------------------------------------------
    def _apply(self, d: Dict[str, Any]) -> None:
        action = d["action"]
        if action in ("grow", "drain"):
            self.supervisor.scale_to(d["to_replicas"],
                                     reason=f"autoscale:{d['rule']}")
        elif action == "retune_shed":
            for name in self.router.models():
                route = self.router.model_route(name)
                if route is None or name in self._shed_saved:
                    continue
                self._shed_saved[name] = (route.bucket.rate,
                                          route.bucket.burst)
                route.bucket.set_rate(d["rows_per_s"])
        elif action == "retune_restore":
            for name, (rate, burst) in list(self._shed_saved.items()):
                route = self.router.model_route(name)
                if route is not None:
                    route.bucket.set_rate(rate, burst_rows=burst)
                del self._shed_saved[name]

    def evaluate(self, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """One control step: snapshot inputs, decide, act (unless
        dry-run), emit one traced ``autoscale`` record per decision
        with the evidence inline."""
        cfg = self.config
        mode = _faults.fire("autoscale.decide")
        if mode == "hang":
            # a wedged controller: block (until stop) WITHOUT touching
            # the fleet — it keeps serving at its current size
            Log.warning("autoscale: decide wedged (injected hang)")
            self._stop.wait()
            return []
        now = self._clock() if now is None else float(now)
        try:
            if mode == "error":
                raise RuntimeError(
                    "injected fault (autoscale.decide:error)")
            with self._lock:
                inp = self.inputs()
                decisions = self.decide(inp, now)
                self.decisions += 1
                mode_str = "dry_run" if cfg.dry_run else "active"
                for d in decisions:
                    with _spans.span("autoscale_decide",
                                     recorder=self.recorder, root=True,
                                     action=d["action"]) as sp:
                        if not cfg.dry_run:
                            self._apply(d)
                            self.actions += 1
                        sp.set(rule=d["rule"], mode=mode_str)
                        self._emit(d, inp, mode_str)
                    Log.info("autoscale[%s]: %s (%s) — burn_fast="
                             "%.2f queue=%.2f replicas=%d",
                             mode_str, d["action"], d["rule"],
                             inp["burn_fast"], inp["queue_frac"],
                             inp["replicas"])
            return decisions
        except Exception as exc:           # noqa: BLE001 - degrade
            # the controller never takes the fleet down with it: an
            # erroring decide leaves everything at current size
            Log.warning("autoscale: decide failed (%s) — fleet left "
                        "at current size", exc)
            if self.recorder is not None:
                self.recorder.emit(
                    "autoscale", action="none", mode="degraded",
                    rule="decide_error", error=str(exc)[:200])
            return []

    def _emit(self, d: Dict[str, Any], inp: Dict[str, Any],
              mode_str: str) -> None:
        if self.recorder is None:
            return
        fields = dict(d)
        fields.pop("action", None)
        fields.pop("rule", None)
        self.recorder.emit(
            "autoscale", action=d["action"], mode=mode_str,
            rule=d["rule"],
            evidence={k: v for k, v in inp.items()
                      if not isinstance(v, bool)},
            **fields)

    def shed_active(self) -> bool:
        return bool(self._shed_saved)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="ltpu-autoscale",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.evaluate()
            except Exception as exc:       # noqa: BLE001 - keep going
                Log.warning("autoscale: loop tick failed: %s", exc)
