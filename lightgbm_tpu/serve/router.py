"""Resilient routing front: the layer above the replica fleet.

``FleetSupervisor`` (PR 6) made one fleet of replicas self-healing,
but clients still round-robined ``endpoints()`` themselves — a dying,
draining or stale-fingerprint replica surfaced as user-visible errors.
The :class:`Router` is the shared-nothing stdlib-HTTP tier that makes
backend failures invisible and opens multi-model tenancy:

- **live-aware balancing** — the router scrapes every backend's
  ``/healthz`` on its own cadence (``route_probe_interval_s``),
  reading health, ``draining`` and the per-tenant ``models``
  fingerprint map, and picks the least-loaded routable backend
  (in-flight count, then the scraped queue depth, round-robin tie
  break).  A mid-drain replica or one whose fingerprint lags the
  fleet's desired model during a deploy never receives a request.
- **failure masking** — every request runs under a total timeout
  budget (``route_timeout_ms``); connect failures and 5xx answers
  retry on a different backend with exponential backoff plus
  deterministic jitter (seeded by request id/attempt — a retry herd
  spreads without flaky tests), bounded by ``route_max_retries`` and
  always clamped to the remaining budget.
- **tail-latency hedging** — once the first attempt has been silent
  ``route_hedge_ms``, a second attempt goes to a DIFFERENT backend;
  the first answer wins and the loser's connection is torn down
  (cancelled losers never feed the circuit breaker or double-count
  request metrics — pinned by ``tests/test_router.py``).
- **circuit breaking** — consecutive forwarding failures open a
  per-backend breaker that feeds the balancer; after
  ``route_breaker_cooldown_s`` the circuit half-opens and exactly ONE
  probe request is let through (single-flight), closing on success.
- **admission budgets** — per-model token buckets (rows/s + burst)
  and in-flight caps shed excess load with a structured 429 +
  ``Retry-After`` BEFORE any backend sees the request; priority > 0
  requests may overdraw one extra burst, so cheap traffic sheds
  first.
- **multi-model tenancy** — a named routing table
  (``POST /v1/<model>/predict``) over the replicas' per-model
  registries (``serve/server.py``), so one fleet serves many boosters
  — the seam the continual daemon's publish tier left open.
- **explanation forwarding** — ``POST /v1/<model>/explain`` (and the
  bare ``/explain`` alias) rides the SAME retry/hedge/breaker/
  admission machinery; explain rows charge the shared token bucket
  weighted by ``route_explain_cost`` (TreeSHAP is O(depth^2) per
  leaf), so an explain burst sheds before it starves predict.

Fault-injection points ``router.backend`` (``sleep_<ms>`` brownout /
``error`` per forwarded attempt) and ``router.admit`` (``shed``) drive
the chaos e2e (``tools/chaos_router.py``) deterministically.  Every
client-facing request emits one ``router`` telemetry record and feeds
the ``ltpu_router_*`` Prometheus series (``GET /metrics``); a routed
request carrying an ``X-Ltpu-Trace`` header stays ONE joinable trace
across client -> router -> replica (``obs/spans.py``,
``tools/trace_view.py``).  See ``docs/Routing.md``.
"""
from __future__ import annotations

import http.client
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from random import Random
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import metrics as _obs_metrics
from ..obs import spans as _spans
from ..utils import faults as _faults
from ..utils.log import Log
from .config import RouterConfig
from .http import split_model_route

__all__ = ["Router", "RouterConfig", "TokenBucket", "CircuitBreaker",
           "backoff_ms", "route_http", "parse_backends_spec"]


def backoff_ms(config: RouterConfig, rid: int, attempt: int) -> float:
    """Retry backoff for ``attempt`` (1-based) of request ``rid``:
    exponential base capped at ``backoff_max_ms`` plus deterministic
    jitter seeded by (seed, rid, attempt) — a pure function, so tests
    replay it exactly and a herd of retries still spreads out."""
    base = min(config.backoff_base_ms * (2 ** max(attempt - 1, 0)),
               config.backoff_max_ms)
    u = Random(config.seed * 1_000_003 + rid * 9176 + attempt).random()
    return base * (1.0 + config.backoff_jitter * u)


class TokenBucket:
    """Per-model admission budget: rows/s refill, ``burst`` capacity.
    ``rate <= 0`` disables (always admits).  Priority > 0 requests may
    overdraw one extra burst (the reserve that keeps important traffic
    flowing while cheap traffic sheds)."""

    def __init__(self, rows_per_s: float, burst_rows: int):
        self.rate = float(rows_per_s)
        self.burst = max(int(burst_rows), 1)
        self._tokens = float(self.burst)
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def set_rate(self, rows_per_s: float,
                 burst_rows: Optional[int] = None) -> None:
        """Retune at runtime (operator surface; the chaos e2e tightens
        a model's budget mid-run)."""
        with self._lock:
            self.rate = float(rows_per_s)
            if burst_rows is not None:
                self.burst = max(int(burst_rows), 1)
                self._tokens = min(self._tokens, float(self.burst))

    def try_take(self, rows: int, priority: int = 0,
                 now: Optional[float] = None) -> float:
        """0.0 when admitted (tokens consumed); otherwise the
        suggested retry-after in ms (nothing consumed).  A request
        larger than the whole burst charges the burst — it could
        never accumulate more tokens than that, so shedding it with
        a finite Retry-After would loop a well-behaved client
        forever (same rule as the serve queue's oversize-on-empty
        admission)."""
        with self._lock:
            if self.rate <= 0:
                return 0.0
            t = time.monotonic() if now is None else now
            self._tokens = min(float(self.burst),
                               self._tokens + (t - self._t) * self.rate)
            self._t = t
            charge = min(int(rows), self.burst)
            floor = -float(self.burst) if priority > 0 else 0.0
            if self._tokens - charge >= floor:
                self._tokens -= charge
                return 0.0
            deficit = charge - (self._tokens - floor)
            return max(deficit / self.rate * 1e3, 1.0)


class CircuitBreaker:
    """Per-backend breaker: ``failures`` consecutive forwarding
    failures open it; after ``cooldown_s`` it half-opens and
    :meth:`acquire` admits exactly ONE probe (single-flight — pinned
    by ``tests/test_router.py``).  The probe's success closes the
    circuit, its failure re-opens it; a CANCELLED probe (hedged loser)
    releases the slot without a verdict."""

    def __init__(self, failures: int, cooldown_s: float):
        self.threshold = max(int(failures), 1)
        self.cooldown_s = float(cooldown_s)
        self.state = "closed"              # closed|open|half_open
        self.failures = 0
        self.opened_at = 0.0
        self._probe_inflight = False
        self._lock = threading.Lock()

    def acquire(self, now: float) -> bool:
        """May an attempt go to this backend now?  Claims the
        half-open probe slot when it grants one."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self.cooldown_s >= 0 and \
                        now - self.opened_at >= self.cooldown_s:
                    self.state = "half_open"
                    self._probe_inflight = True
                    return True
                return False
            # half_open: single-flight
            if not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def on_success(self) -> bool:
        """Returns True when this success CLOSED an open/half-open
        circuit (the breaker_close telemetry event)."""
        with self._lock:
            was = self.state != "closed"
            self.state = "closed"
            self.failures = 0
            self._probe_inflight = False
            return was

    def on_failure(self, now: float) -> bool:
        """Returns True when this failure OPENED the circuit (the
        breaker_open telemetry event)."""
        with self._lock:
            self._probe_inflight = False
            self.failures += 1
            if self.state == "half_open" or \
                    self.failures >= self.threshold:
                newly = self.state != "open"
                self.state = "open"
                self.opened_at = now
                return newly
            return False

    def on_cancel(self) -> None:
        """A cancelled attempt (hedged loser) reached no verdict: it
        must neither open nor close the circuit, only release the
        half-open probe slot it may hold."""
        with self._lock:
            self._probe_inflight = False


class _Backend:
    """One replica URL with the router's live view of it."""

    __slots__ = ("url", "host", "port", "healthy", "draining", "models",
                 "queue_rows", "inflight", "breaker")

    def __init__(self, url: str, breaker: CircuitBreaker):
        self.url = url.rstrip("/")
        u = urllib.parse.urlsplit(self.url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.healthy = False
        self.draining = False
        self.models: Dict[str, Optional[str]] = {}
        self.queue_rows = 0
        self.inflight = 0
        self.breaker = breaker


class _ModelRoute:
    """One routing-table entry: a named model over a backend source."""

    __slots__ = ("name", "replica_model", "source", "desired_fp",
                 "bucket", "max_inflight", "inflight", "urls")

    def __init__(self, name: str, source: Callable[[], List[str]],
                 desired_fp: Optional[Callable[[], Optional[str]]],
                 replica_model: str, bucket: TokenBucket,
                 max_inflight: int):
        self.name = name
        self.replica_model = replica_model
        self.source = source
        self.desired_fp = desired_fp
        self.bucket = bucket
        self.max_inflight = int(max_inflight)
        self.inflight = 0
        self.urls: List[str] = []


class _Attempt:
    __slots__ = ("backend", "is_hedge", "conn", "cancelled", "done")

    def __init__(self, backend: _Backend, is_hedge: bool):
        self.backend = backend
        self.is_hedge = is_hedge
        self.conn: Optional[http.client.HTTPConnection] = None
        self.cancelled = False
        self.done = False


class _Result:
    __slots__ = ("code", "body", "status", "attempts", "retries",
                 "hedged", "hedge_won", "backend", "headers")

    def __init__(self, code: int, body: bytes, status: str,
                 attempts: int = 0, retries: int = 0,
                 hedged: bool = False, hedge_won: bool = False,
                 backend: str = "",
                 headers: Optional[Dict[str, str]] = None):
        self.code = code
        self.body = body
        self.status = status
        self.attempts = attempts
        self.retries = retries
        self.hedged = hedged
        self.hedge_won = hedge_won
        self.backend = backend
        self.headers = headers or {}


def _json_result(code: int, status: str, obj: Dict[str, Any],
                 **kw) -> _Result:
    return _Result(code, json.dumps(obj).encode(), status, **kw)


def parse_backends_spec(spec: str) -> Dict[str, List[str]]:
    """Parse the ``route_backends`` static table: comma-separated
    ``http://host:port`` entries (default tenant) or
    ``name=http://a+http://b`` (named tenant over several URLs)."""
    out: Dict[str, List[str]] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, urls = part.split("=", 1)
            name = name.strip()
        else:
            name, urls = "default", part
        if not name:
            raise ValueError(f"route_backends entry {part!r}: empty "
                             f"model name")
        for url in urls.split("+"):
            url = url.strip()
            if not url.startswith("http://"):
                # the forwarding client is plain http.client — an
                # https backend would be spoken to in CLEARTEXT, so
                # reject it loudly at config time
                raise ValueError(f"route_backends entry {part!r}: "
                                 f"{url!r} must be an http:// URL "
                                 f"(TLS termination belongs in front "
                                 f"of the router)")
            out.setdefault(name, []).append(url)
    return out


class Router:
    """The routing front; see the module docstring.  Models are added
    with :meth:`add_model` (a FleetSupervisor, or static URLs), then
    :meth:`start` begins the scrape loop and :func:`route_http` (or
    ``task=route``) serves clients."""

    def __init__(self, config: Optional[RouterConfig] = None,
                 recorder=None):
        self.config = config or RouterConfig()
        self.config.validate()
        self.recorder = recorder
        self._routes: Dict[str, _ModelRoute] = {}
        self._backends: Dict[str, _Backend] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.draining = False
        self._rid = 0
        self._rr = 0
        self._counts: Dict[str, int] = {}
        self._hedges = 0
        self._hedge_wins = 0
        self._retries_total = 0
        lat_buckets = _obs_metrics.DEFAULT_LATENCY_BUCKETS_MS
        self._lat_hist = _obs_metrics.RollingHistogram(
            buckets=lat_buckets)
        self._metrics = self._make_metrics(lat_buckets) \
            if self.config.metrics else None

    # -- metrics -------------------------------------------------------
    def _make_metrics(self, lat_buckets) -> Dict[str, Any]:
        _obs_metrics.install_telemetry_mirror()
        reg = _obs_metrics.get_registry()
        m = {
            "requests": reg.counter(
                "ltpu_router_requests_total",
                "client-facing routed requests by terminal status",
                ("status",)),
            "rows": reg.counter(
                "ltpu_router_rows_total",
                "rows in terminal routed requests", ("status",)),
            "attempts": reg.counter(
                "ltpu_router_attempts_total",
                "backend forwarding attempts by outcome (cancelled = "
                "hedged loser, not a backend failure)", ("result",)),
            "hedges": reg.counter(
                "ltpu_router_hedges_total",
                "tail-latency hedges by result", ("result",)),
            "retries": reg.counter(
                "ltpu_router_retries_total", "forwarding retries"),
            "shed": reg.counter(
                "ltpu_router_shed_total",
                "requests shed by the per-model admission budget",
                ("model",)),
            "latency": reg.histogram(
                "ltpu_router_latency_ms",
                "total routed latency (ok requests)",
                buckets=lat_buckets),
        }
        m["lat_child"] = m["latency"].labels()
        m["req_children"] = {}
        m["att_children"] = {}
        m["gauges"] = {
            "ltpu_router_backends_routable":
                ("backends currently routable (healthy, not draining)",
                 lambda: float(sum(
                     1 for b in list(self._backends.values())
                     if b.healthy and not b.draining))),
            "ltpu_router_inflight":
                ("routed requests currently in flight",
                 lambda: float(sum(r.inflight for r in
                                   list(self._routes.values())))),
            "ltpu_router_breakers_open":
                ("backend circuit breakers currently open",
                 lambda: float(sum(
                     1 for b in list(self._backends.values())
                     if b.breaker.state == "open"))),
        }
        for name, (help_, fn) in m["gauges"].items():
            reg.gauge_callback(name, fn, help_)
        return m

    def _metric_req(self, status: str):
        ch = self._metrics["req_children"].get(status)
        if ch is None:                     # benign race: idempotent
            ch = (self._metrics["requests"].labels(status=status),
                  self._metrics["rows"].labels(status=status))
            self._metrics["req_children"][status] = ch
        return ch

    def _metric_attempt(self, result: str) -> None:
        if self._metrics is None:
            return
        ch = self._metrics["att_children"].get(result)
        if ch is None:
            ch = self._metrics["attempts"].labels(result=result)
            self._metrics["att_children"][result] = ch
        ch.inc()

    def _emit(self, event: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.emit("router", event=event, **fields)

    # -- routing table -------------------------------------------------
    def add_model(self, name: str, supervisor=None,
                  urls: Optional[List[str]] = None,
                  replica_model: Optional[str] = None,
                  rows_per_s: Optional[float] = None,
                  burst_rows: Optional[int] = None,
                  max_inflight: Optional[int] = None) -> None:
        """Register a named model over a backend source: a
        :class:`~.fleet.FleetSupervisor` (live slot URLs + the desired
        fingerprint, so stale replicas are excluded during a deploy)
        or a static URL list.  ``replica_model`` is the tenant name on
        the replicas (defaults to ``name``); budget knobs default to
        the ``route_*`` config."""
        if supervisor is None and urls is None:
            raise ValueError("add_model needs a supervisor or urls")
        for u in urls or ():
            if not u.startswith("http://"):
                raise ValueError(f"backend {u!r} must be an http:// "
                                 f"URL (the router forwards plain "
                                 f"HTTP)")
        rep = replica_model if replica_model is not None else name
        if supervisor is not None:
            def source(sup=supervisor):
                return [s["url"] for s in sup.slots() if s["url"]]

            def desired(sup=supervisor, rep=rep):
                return sup.desired_fingerprint(rep)
        else:
            frozen = [u.rstrip("/") for u in urls]

            def source(frozen=frozen):
                return list(frozen)
            desired = None
        bucket = TokenBucket(
            self.config.rows_per_s if rows_per_s is None else rows_per_s,
            self.config.burst_rows if burst_rows is None else burst_rows)
        route = _ModelRoute(
            name, source, desired, rep, bucket,
            self.config.max_inflight if max_inflight is None
            else max_inflight)
        with self._lock:
            self._routes[name] = route

    def model_route(self, name: str) -> Optional[_ModelRoute]:
        """The live routing-table entry (operator surface: retune
        ``route.bucket`` / ``route.max_inflight`` at runtime)."""
        with self._lock:
            return self._routes.get(name)

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._routes)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "Router":
        if self._thread is not None:
            return self
        self._scrape()                     # synchronous first view
        self._stop.clear()
        self._thread = threading.Thread(target=self._scrape_loop,
                                        name="ltpu-router", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.draining = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._metrics is not None:
            reg = _obs_metrics.get_registry()
            for name, (_help, fn) in self._metrics["gauges"].items():
                reg.release_gauge_callback(name, fn)

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- scraping ------------------------------------------------------
    def _scrape_loop(self) -> None:
        while not self._stop.wait(self.config.probe_interval_s):
            try:
                self._scrape()
            except Exception as exc:       # noqa: BLE001 - keep going
                Log.warning("router: scrape tick failed: %s", exc)

    def _scrape(self) -> None:
        with self._lock:
            routes = list(self._routes.values())
        live: set = set()
        for route in routes:
            try:
                urls = [u.rstrip("/") for u in route.source() if u]
            except Exception as exc:       # noqa: BLE001 - source flaky
                Log.warning("router: backend source for %r failed: %s",
                            route.name, exc)
                continue
            with self._lock:
                route.urls = urls
            live.update(urls)
        with self._lock:
            for url in live:
                if url not in self._backends:
                    self._backends[url] = _Backend(
                        url, CircuitBreaker(
                            self.config.breaker_failures,
                            self.config.breaker_cooldown_s))
            stale = [u for u in self._backends if u not in live]
            for u in stale:
                del self._backends[u]
            targets = list(self._backends.values())

        def probe_one(b: _Backend) -> None:
            ok, body = self._probe(b.url)
            if body is None:
                b.healthy = False
                b.draining = False
                b.models = {}
                return
            b.draining = bool(body.get("draining"))
            b.healthy = ok and not b.draining
            models = body.get("models")
            b.models = dict(models) if isinstance(models, dict) else \
                {"default": body.get("model_id")}
            b.queue_rows = int(body.get("queue_rows", 0) or 0)

        # probe CONCURRENTLY: one hung backend (accepts, never
        # answers) must not stall the whole fleet's health view past
        # the advertised cadence — a draining/stale replica still
        # leaves the rotation within ~one interval + probe timeout
        if len(targets) <= 1:
            for b in targets:
                probe_one(b)
        else:
            probers = [threading.Thread(target=probe_one, args=(b,),
                                        name="ltpu-router-probe",
                                        daemon=True) for b in targets]
            for t in probers:
                t.start()
            for t in probers:
                t.join(self.config.probe_timeout_s + 1.0)

    def _probe(self, url: str):
        try:
            with urllib.request.urlopen(
                    url + "/healthz",
                    timeout=self.config.probe_timeout_s) as r:
                obj = json.loads(r.read())
            return bool(obj.get("ok")), obj
        except urllib.error.HTTPError as e:
            # a draining replica answers 503 with a JSON body — that
            # is information, not a dead backend
            try:
                return False, json.loads(e.read())
            except Exception:              # noqa: BLE001 - probe only
                return False, None
        except Exception:                  # noqa: BLE001 - probe only
            return False, None

    # -- balancing -----------------------------------------------------
    def _pick(self, route: _ModelRoute, exclude: set,
              now: float) -> Optional[_Backend]:
        """Least-loaded routable backend not in ``exclude`` whose
        breaker admits an attempt (claiming the half-open probe slot
        when it does).  Routable = scraped healthy, not draining,
        serving the tenant, and — when the route knows its desired
        fingerprint (a supervisor-attached model mid-deploy) —
        serving the CURRENT fingerprint."""
        with self._lock:
            urls = list(route.urls)
            backends = dict(self._backends)
        want = route.desired_fp() if route.desired_fp is not None \
            else None
        cands: List[_Backend] = []
        for url in urls:
            b = backends.get(url)
            if b is None or not b.healthy or b.draining:
                continue
            if url in exclude:
                continue
            fp = b.models.get(route.replica_model)
            if fp is None:
                continue                   # tenant not on this replica
            if want is not None and fp != want:
                continue                   # stale mid-deploy
            cands.append(b)
        if not cands:
            return None
        # round-robin rotation, then a stable least-loaded sort: equal
        # loads spread across the fleet instead of camping on slot 0
        with self._lock:
            off = self._rr % len(cands)
            self._rr += 1
        cands = cands[off:] + cands[:off]
        cands.sort(key=lambda b: (b.inflight, b.queue_rows))
        for b in cands:
            if b.breaker.acquire(now):
                return b
        return None

    # -- the request engine --------------------------------------------
    def route_request(self, model: str, raw_body: bytes, rows: int,
                      priority: int = 0,
                      timeout_ms: Optional[float] = None,
                      carrier: Optional[Tuple[str, str]] = None,
                      verb: str = "/predict") -> _Result:
        """Route one predict or explain request: admission budget ->
        balanced forwarding with retries + hedging inside the timeout
        budget.  ``verb`` ("/predict" | "/explain") selects the
        backend route; explain rows charge the token bucket weighted
        by ``route_explain_cost``.  Returns the client-facing
        :class:`_Result` (the backend's body passes through
        byte-identical on success; router metadata rides response
        headers)."""
        t0 = time.monotonic()
        with self._lock:
            self._rid += 1
            rid = self._rid
            route = self._routes.get(model)
        if route is None:
            return self._finish(rid, model, rows, t0, _json_result(
                404, "unknown_model",
                {"error": f"no model {model!r} in the routing table",
                 "code": "unknown_model"}), verb)
        # -- admission budget (before any backend sees the request).
        # The in-flight cap is checked AND claimed in one critical
        # section (concurrent admissions cannot overshoot it), and it
        # is checked BEFORE the token bucket so a cap-shed request
        # never silently drains budget tokens it won't use.
        retry_ms = 0.0
        admitted_inflight = False
        if route.max_inflight > 0:
            cap = route.max_inflight * (2 if priority > 0 else 1)
            with self._lock:
                if route.inflight >= cap:
                    retry_ms = 50.0
                else:
                    route.inflight += 1
                    admitted_inflight = True
        try:
            if retry_ms <= 0:
                # explain rows cost more device work than predict
                # rows; weight them so the shared budget stays honest
                cost = rows if verb != "/explain" else \
                    int(-(-rows * self.config.explain_cost // 1))
                retry_ms = route.bucket.try_take(cost, priority)
            if _faults.fire("router.admit") == "shed":
                retry_ms = max(retry_ms, 1.0)
            if retry_ms > 0:
                if self._metrics is not None:
                    self._metrics["shed"].labels(model=model).inc()
                retry_s = max(int(-(-retry_ms // 1e3)), 1)
                return self._finish(rid, model, rows, t0, _json_result(
                    429, "shed",
                    {"error": f"admission budget exhausted for model "
                              f"{model!r}", "code": "backpressure",
                     "retry_after_ms": round(retry_ms, 1)},
                    headers={"Retry-After": str(retry_s)}), verb)
            budget_ms = self.config.timeout_ms
            if timeout_ms is not None and timeout_ms > 0:
                budget_ms = min(budget_ms, float(timeout_ms))
            deadline = t0 + budget_ms / 1e3
            fwd_headers = {"Content-Type": "application/json"}
            if carrier is not None:
                fwd_headers[_spans.HTTP_HEADER] = \
                    f"{carrier[0]}:{carrier[1]}"
            if route.max_inflight <= 0:
                with self._lock:
                    route.inflight += 1
                admitted_inflight = True
            res = self._attempt_loop(route, raw_body, rid, deadline,
                                     fwd_headers, verb)
        finally:
            if admitted_inflight:
                with self._lock:
                    route.inflight -= 1
        return self._finish(rid, model, rows, t0, res, verb)

    def _finish(self, rid: int, model: str, rows: int, t0: float,
                res: _Result, verb: str = "/predict") -> _Result:
        total_ms = round((time.monotonic() - t0) * 1e3, 3)
        with self._lock:
            self._counts[res.status] = \
                self._counts.get(res.status, 0) + 1
            if res.hedged:
                self._hedges += 1
                if res.hedge_won:
                    self._hedge_wins += 1
            self._retries_total += res.retries
        if res.status == "ok":
            self._lat_hist.observe(total_ms)
        if self._metrics is not None:
            c_req, c_rows = self._metric_req(res.status)
            c_req.inc()
            c_rows.inc(rows)
            if res.status == "ok":
                self._metrics["lat_child"].observe(total_ms)
            if res.retries:
                self._metrics["retries"].inc(res.retries)
            if res.hedged:
                self._metrics["hedges"].labels(
                    result="win" if res.hedge_won else "loss").inc()
        fields: Dict[str, Any] = {
            "status": res.status, "model": model, "rows": rows,
            "total_ms": total_ms, "attempts": res.attempts,
            "retries": res.retries, "rid": rid,
        }
        if verb != "/predict":
            fields["verb"] = verb
        if res.hedged:
            fields["hedged"] = True
            fields["hedge_won"] = bool(res.hedge_won)
        if res.backend:
            fields["backend"] = res.backend
        self._emit("request", **fields)
        res.headers.setdefault("X-Ltpu-Router-Attempts",
                               str(res.attempts))
        if res.backend:
            res.headers.setdefault("X-Ltpu-Router-Backend", res.backend)
        return res

    def _attempt_loop(self, route: _ModelRoute, raw_body: bytes,
                      rid: int, deadline: float,
                      fwd_headers: Dict[str, str],
                      verb: str = "/predict") -> _Result:
        cond = threading.Condition()
        state: Dict[str, Any] = {"winner": None, "failures": [],
                                 "live": 0}
        attempts: List[_Attempt] = []
        used: set = set()
        retries_left = self.config.max_retries
        n_retries = 0
        hedged = False
        hedge_won = False
        first_error = ""

        def launch(backend: _Backend, is_hedge: bool) -> _Attempt:
            att = _Attempt(backend, is_hedge)
            attempts.append(att)
            used.add(backend.url)
            with self._lock:
                backend.inflight += 1
            with cond:
                state["live"] += 1
            threading.Thread(
                target=self._run_attempt,
                args=(att, route, raw_body, deadline, fwd_headers,
                      cond, state, verb),
                name="ltpu-route-attempt", daemon=True).start()
            return att

        now = time.monotonic()
        b = self._pick(route, used, now)
        if b is None:
            # convergence grace: a just-published tenant (or a fleet
            # mid-restart) can lag the scrape by one interval — wait a
            # bounded beat for the view to catch up before 503ing
            grace = min(deadline,
                        now + max(3 * self.config.probe_interval_s,
                                  0.5))
            while b is None and time.monotonic() < grace:
                time.sleep(self.config.probe_interval_s / 2)
                b = self._pick(route, used, time.monotonic())
        if b is None:
            return _json_result(
                503, "no_backend",
                {"error": f"no routable backend for model "
                          f"{route.name!r}", "code": "no_backend"},
                headers={"Retry-After": "1"})
        launch(b, False)
        # the hedge timer starts when the attempt LAUNCHES — after
        # any convergence-grace wait above, or a stale `now` would
        # fire the hedge immediately on every grace-delayed request
        now = time.monotonic()
        hedge_at = now + self.config.hedge_ms / 1e3 \
            if self.config.hedge_ms > 0 else None

        def cancel_losers(winner_att: Optional[_Attempt]) -> None:
            with cond:
                losers = [a for a in attempts
                          if a is not winner_att and not a.done]
                for a in losers:
                    a.cancelled = True
            for a in losers:
                conn = a.conn
                if conn is not None:
                    try:
                        conn.close()       # tears the socket: the
                    except Exception:      # noqa: BLE001
                        pass               # loser unblocks + self-cleans

        while True:
            fail = None
            with cond:
                if state["winner"] is None and not state["failures"]:
                    now = time.monotonic()
                    wait_until = deadline
                    if not hedged and hedge_at is not None:
                        wait_until = min(wait_until, hedge_at)
                    if now < wait_until:
                        cond.wait(max(wait_until - now, 0.001))
                if state["winner"] is not None:
                    att, status, body, retry_after = state["winner"]
                    hedge_won = att.is_hedge
                    winner = att
                else:
                    winner = None
                    if state["failures"]:
                        fail = state["failures"].pop(0)
                    live = state["live"]
            now = time.monotonic()
            if winner is not None:
                cancel_losers(winner)
                hdrs: Dict[str, str] = {}
                if retry_after:
                    hdrs["Retry-After"] = retry_after
                # winners are definitive answers only (_run_attempt
                # classifies 429/5xx as retryable failures): 200 or a
                # passed-through client-fault 4xx
                out_status = "ok" if status == 200 else "bad_request"
                return _Result(status, body, out_status,
                               attempts=len(attempts),
                               retries=n_retries, hedged=hedged,
                               hedge_won=hedged and hedge_won,
                               backend=winner.backend.url,
                               headers=hdrs)
            if fail is not None:
                first_error = first_error or fail[1]
                if live > 0:
                    continue               # a hedge is still running
                if retries_left <= 0 or now >= deadline:
                    st_f, ra = fail[2], fail[3]
                    if st_f in (429, 503):
                        # every backend answered backpressure: pass
                        # it through STRUCTURED, preserving the
                        # Retry-After hint, so well-behaved clients
                        # can still back off correctly.  Status
                        # "backpressure" (NOT "shed"): backend
                        # saturation is a different signal from the
                        # router's own admission budget, and the
                        # shed-rate anomaly must not fire for it
                        try:
                            ra_ms = max(float(ra) * 1e3, 1.0)
                        except (TypeError, ValueError):
                            ra_ms = 1000.0
                        return _json_result(
                            st_f, "backpressure",
                            {"error": f"all {len(attempts)} "
                                      f"attempt(s) backpressured; "
                                      f"last: {fail[1][:160]}",
                             "code": "backpressure",
                             "retry_after_ms": round(ra_ms, 1)},
                            attempts=len(attempts),
                            retries=n_retries, hedged=hedged,
                            headers={"Retry-After": ra or "1"})
                    if st_f == 504:
                        return _json_result(
                            504, "timeout",
                            {"error": f"backend deadline expired on "
                                      f"all {len(attempts)} "
                                      f"attempt(s)",
                             "code": "timeout"},
                            attempts=len(attempts),
                            retries=n_retries, hedged=hedged)
                    return _json_result(
                        502, "upstream",
                        {"error": f"all {len(attempts)} attempt(s) "
                                  f"failed; last: {first_error[:200]}",
                         "code": "upstream"},
                        attempts=len(attempts), retries=n_retries,
                        hedged=hedged)
                retries_left -= 1
                n_retries += 1
                pause = backoff_ms(self.config, rid, n_retries) / 1e3
                pause = min(pause, max(deadline - now - 0.005, 0.0))
                if pause > 0:
                    time.sleep(pause)
                now = time.monotonic()
                b = self._pick(route, used, now) or \
                    self._pick(route, set(), now)
                if b is None:
                    return _json_result(
                        503, "no_backend",
                        {"error": f"no routable backend left for "
                                  f"model {route.name!r} after "
                                  f"{len(attempts)} attempt(s)",
                         "code": "no_backend"},
                        attempts=len(attempts), retries=n_retries,
                        hedged=hedged,
                        headers={"Retry-After": "1"})
                launch(b, False)
                # re-arm the hedge timer: the NEW attempt earns its
                # own silence window — a stale timer would hedge
                # every retry instantly, doubling backend load during
                # a plain failure-retry storm
                if hedge_at is not None:
                    hedge_at = time.monotonic() + \
                        self.config.hedge_ms / 1e3
                continue
            if not hedged and hedge_at is not None and \
                    now >= hedge_at and live == 1:
                b = self._pick(route, used, now)
                if b is not None:
                    hedged = True
                    launch(b, True)
                else:
                    hedge_at = None        # nobody to hedge to
                continue
            if now >= deadline:
                cancel_losers(None)
                return _json_result(
                    504, "timeout",
                    {"error": f"routing budget "
                              f"({self.config.timeout_ms:.0f} ms "
                              f"cap) exhausted", "code": "timeout"},
                    attempts=len(attempts), retries=n_retries,
                    hedged=hedged)

    def _run_attempt(self, att: _Attempt, route: _ModelRoute,
                     raw_body: bytes, deadline: float,
                     fwd_headers: Dict[str, str], cond, state,
                     verb: str = "/predict") -> None:
        status = None
        body = b""
        retry_after = ""
        err: Optional[str] = None
        err_timeout = False
        try:
            # fault point ``router.backend``: sleep_<ms> = injected
            # brownout on this attempt (the hedge must race around
            # it), sleepb<i>_<ms> = brownout pinned to ONE backend
            # (index i in the route's URL order — the "one slow
            # replica" scenario the hedging bench measures), error =
            # the connection dies
            mode = _faults.fire("router.backend")
            if mode.startswith("sleep_"):
                time.sleep(max(float(mode.split("_", 1)[1]), 0.0) / 1e3)
            elif mode.startswith("sleepb"):
                idx_s, ms_s = mode[6:].split("_", 1)
                with self._lock:
                    urls = list(route.urls)
                if int(idx_s) < len(urls) and \
                        att.backend.url == urls[int(idx_s)]:
                    time.sleep(max(float(ms_s), 0.0) / 1e3)
            elif mode == "error":
                raise OSError("injected fault (router.backend:error)")
            timeout = max(deadline - time.monotonic(), 0.05)
            conn = http.client.HTTPConnection(
                att.backend.host, att.backend.port, timeout=timeout)
            att.conn = conn
            rep = route.replica_model
            path = verb if rep == "default" else f"/v1/{rep}{verb}"
            conn.request("POST", path, raw_body, headers=fwd_headers)
            resp = conn.getresponse()
            body = resp.read()
            status = resp.status
            retry_after = resp.headers.get("Retry-After", "") or ""
        except Exception as exc:           # noqa: BLE001 - classified
            err = f"{type(exc).__name__}: {exc}"
            # socket.timeout is the CLIENT's remaining budget
            # expiring, not the backend misbehaving — a tight
            # per-request timeout_ms must not open breakers on
            # healthy backends (same policy as a backend 504)
            err_timeout = isinstance(exc, TimeoutError)
        finally:
            with self._lock:
                att.backend.inflight -= 1
        now = time.monotonic()
        opened = False
        with cond:
            state["live"] -= 1
            att.done = True
            if att.cancelled:
                # hedged loser torn down by the winner: no verdict —
                # neither a breaker event nor a second request count
                att.backend.breaker.on_cancel()
                self._metric_attempt("cancelled")
                cond.notify_all()
                return
            if err is None and status is not None and \
                    status not in (429, 500, 502, 503, 504):
                # a definitive answer (2xx or a client-fault 4xx):
                # pass it through; first definitive answer wins
                closed = att.backend.breaker.on_success()
                self._metric_attempt("ok")
                if state["winner"] is None:
                    state["winner"] = (att, status, body, retry_after)
                cond.notify_all()
                if closed:
                    self._emit("breaker_close",
                               backend=att.backend.url)
                return
            # retryable failure: transport error or 5xx/429.  Only
            # breaker-penalize genuine backend faults (transport, 500,
            # 502) — 429/503/504 are the backend doing admission
            # control, not being broken.
            detail = err if err is not None else \
                f"HTTP {status}: {body[:120]!r}"
            if (err is not None and not err_timeout) or \
                    status in (500, 502):
                opened = att.backend.breaker.on_failure(now)
            else:
                att.backend.breaker.on_cancel()
            self._metric_attempt("error")
            state["failures"].append((att, detail, status,
                                      retry_after))
            cond.notify_all()
        if opened:
            Log.warning("router: circuit OPEN on backend %s (%s)",
                        att.backend.url, detail[:120])
            self._emit("breaker_open", backend=att.backend.url,
                       failures=att.backend.breaker.failures,
                       detail=str(detail)[:200])

    # -- introspection -------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            routes = dict(self._routes)
            backends = dict(self._backends)
            counts = dict(self._counts)
            hedges, wins = self._hedges, self._hedge_wins
            retries = self._retries_total
        return {
            "draining": self.draining,
            "models": {
                name: {
                    "backends": list(r.urls),
                    "inflight": r.inflight,
                    "max_inflight": r.max_inflight,
                    "rows_per_s": r.bucket.rate,
                    "desired": r.desired_fp()
                    if r.desired_fp is not None else None,
                } for name, r in routes.items()},
            "backends": {
                url: {
                    "healthy": b.healthy, "draining": b.draining,
                    "inflight": b.inflight,
                    "queue_rows": b.queue_rows,
                    "breaker": b.breaker.state,
                    "models": dict(b.models),
                } for url, b in backends.items()},
            "requests": counts,
            "hedges": hedges, "hedge_wins": wins, "retries": retries,
            "latency_ms": {
                "p50": round(self._lat_hist.percentile(0.50), 3),
                "p95": round(self._lat_hist.percentile(0.95), 3),
                "p99": round(self._lat_hist.percentile(0.99), 3),
            },
        }

    def healthz(self) -> Dict[str, Any]:
        with self._lock:
            routes = dict(self._routes)
            backends = dict(self._backends)
        routable = {
            name: sum(1 for u in r.urls
                      if (b := backends.get(u)) is not None
                      and b.healthy and not b.draining)
            for name, r in routes.items()}
        return {"ok": not self.draining, "draining": self.draining,
                "role": "router", "models": routable,
                "backends": len(backends)}

    def metrics_text(self) -> str:
        return _obs_metrics.render()


# ----------------------------------------------------------------------
# HTTP front
# ----------------------------------------------------------------------
def _router_handler_for(router: Router):
    class RouteHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _send(self, code: int, body: bytes,
                  headers: Optional[Dict[str, str]] = None,
                  content_type: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, obj: Dict[str, Any],
                       headers: Optional[Dict[str, str]] = None
                       ) -> None:
            self._send(code, json.dumps(obj).encode(), headers)

        def log_message(self, fmt, *args):
            Log.debug("router http: " + fmt, *args)

        def do_GET(self):
            try:
                self._get()
            except (BrokenPipeError, ConnectionResetError):
                pass
            except Exception as exc:       # noqa: BLE001 - last resort
                Log.warning("router http: unhandled %s: %s",
                            type(exc).__name__, exc)

        def _get(self):
            if self.path == "/healthz":
                body = router.healthz()
                self._send_json(503 if router.draining else 200, body)
            elif self.path == "/stats":
                self._send_json(200, router.stats())
            elif self.path == "/metrics":
                if not router.config.metrics:
                    self._send_json(404, {"error": "metrics are off",
                                          "code": "no_route"})
                else:
                    self._send(200, router.metrics_text().encode(),
                               content_type="text/plain; "
                                            "version=0.0.4")
            else:
                self._send_json(404, {"error": f"no route {self.path}",
                                      "code": "no_route"})

        def do_POST(self):
            try:
                self._post()
            except (BrokenPipeError, ConnectionResetError):
                pass
            except Exception as exc:       # noqa: BLE001 - last resort
                Log.warning("router http: unhandled %s: %s",
                            type(exc).__name__, exc)
                try:
                    self._send_json(500, {"error": f"internal: {exc}",
                                          "code": "internal"})
                except Exception:          # noqa: BLE001 - socket dead
                    pass

        def _post(self):
            model, verb = split_model_route(self.path)
            if verb not in ("/predict", "/explain"):
                self._send_json(404, {"error": f"no route {self.path}",
                                      "code": "no_route"})
                return
            if router.draining:
                self.close_connection = True
                self._send_json(503, {"error": "router is draining",
                                      "code": "draining",
                                      "draining": True},
                                headers={"Retry-After": "1"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
            except (TypeError, ValueError):
                n = -1
            if n < 0 or n > router.config.max_body_bytes:
                self.close_connection = True
                self._send_json(
                    413 if n > 0 else 400,
                    {"error": f"bad or oversized body ({n} bytes)",
                     "code": "body_too_large" if n > 0
                     else "bad_content_length"})
                return
            raw = self.rfile.read(n) if n else b"{}"
            try:
                obj = json.loads(raw or b"{}")
                rows_field = obj["rows"]
                rows = len(rows_field)
                if not isinstance(rows_field, list) or rows == 0:
                    raise ValueError("rows must be a non-empty list")
                priority = int(obj.get("priority", 0))
                timeout_ms = obj.get("timeout_ms")
                if timeout_ms is not None:
                    timeout_ms = float(timeout_ms)
            except (KeyError, ValueError, TypeError) as exc:
                self.close_connection = True
                self._send_json(400, {"error": f"bad request body: "
                                               f"{exc}",
                                      "code": "bad_rows"})
                return
            # enter the client's trace context (X-Ltpu-Trace): the
            # router record joins it, and the carrier forwards to the
            # replica — client -> router -> replica stays ONE trace
            carrier = _spans.from_headers(self.headers)
            with _spans.use(carrier):
                res = router.route_request(
                    model or "default", raw, rows, priority=priority,
                    timeout_ms=timeout_ms, carrier=carrier, verb=verb)
            self._send(res.code, res.body, res.headers)

    return RouteHandler


def route_http(router: Router, host: Optional[str] = None,
               port: Optional[int] = None, background: bool = False
               ) -> Tuple[ThreadingHTTPServer,
                          Optional[threading.Thread]]:
    """Start the router's scrape loop and HTTP front.  With
    ``background=True`` the accept loop runs in a daemon thread and
    returns immediately; otherwise this blocks until SIGTERM/SIGINT,
    then drains (new work 503s, the accept loop closes)."""
    router.start()
    host = router.config.host if host is None else host
    port = router.config.port if port is None else port
    httpd = ThreadingHTTPServer((host, port),
                                _router_handler_for(router))
    httpd.daemon_threads = True
    if router.config.port_file:
        tmp = router.config.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write("%d\n" % httpd.server_address[1])
        os.replace(tmp, router.config.port_file)
    Log.info("router: listening on http://%s:%d (models: %s)",
             *httpd.server_address[:2],
             ",".join(router.models()) or "-")
    accept = threading.Thread(target=httpd.serve_forever,
                              name="ltpu-router-http", daemon=True)
    accept.start()
    if background:
        return httpd, accept

    stop_evt = threading.Event()
    previous: Dict[int, Any] = {}

    def _on_signal(signum, frame):
        Log.info("router: signal %d — draining", signum)
        stop_evt.set()

    installed = False
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGINT):
            previous[sig] = signal.signal(sig, _on_signal)
        installed = True
    try:
        stop_evt.wait()
    except KeyboardInterrupt:
        pass
    finally:
        try:
            router.draining = True
            time.sleep(0.2)                # let in-flight responses out
        finally:
            httpd.shutdown()
            httpd.server_close()
            router.stop()
            if installed:
                for sig, old in previous.items():
                    signal.signal(sig, old)
    Log.info("router: drained and stopped")
    return httpd, None
