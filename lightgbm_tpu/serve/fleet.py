"""Replica supervisor: one serve process is a single point of failure;
a supervised fleet is not.

``FleetSupervisor`` runs N replicas (shared-nothing serve stacks, each
pinning its own engine cache), probes ``/healthz``, and treats a dead
process and a hung-but-alive one identically: after
``fleet_fail_threshold`` consecutive failed probes (or immediately on
process exit) the replica is killed and restarted with exponential
backoff plus deterministic jitter.  ``fleet_circuit_failures``
consecutive failures open a circuit breaker — the slot leaves the
rotation and the fleet degrades gracefully instead of burning CPU on a
crash loop; after ``fleet_circuit_cooldown_s`` the circuit half-opens
and one restart is retried.

The supervisor is also the fleet's model-state reconciler: the desired
model (set by :meth:`FleetSupervisor.publish_model`, normally from the
checkpoint watcher) is swapped onto every healthy replica, and a
restarted replica — which comes back serving its original
``input_model`` — is re-swapped to the desired model BEFORE it rejoins
the rotation, so a crash mid-deploy cannot reintroduce an old version.

Replica handles come in two shapes behind one duck-typed interface
(``start() -> url``, ``alive()``, ``terminate(grace_s)``, ``kill()``):

- :class:`InprocReplica` — a full serve stack (Server + HTTP front) in
  daemon threads of THIS process; ``kill()`` closes the listening
  socket abruptly (no drain).  The unit-test replica: fast, and a kill
  looks exactly like a crash to probes and clients.
- :class:`ProcessReplica` — ``python -m lightgbm_tpu task=serve`` in a
  subprocess with ``serve_port=0`` + ``serve_port_file`` ephemeral-port
  discovery.  The chaos-harness replica (``tools/loadgen_serve.py
  --fleet``, the CI chaos job): ``kill()`` is a real SIGKILL,
  ``terminate()`` a SIGTERM that triggers the graceful drain.

Fault-injection point ``fleet.spawn`` (mode ``fail``) makes replica
spawn raise, exercising the backoff/circuit path deterministically.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from random import Random
from typing import Any, Callable, Dict, List, Optional

from ..obs import metrics as _obs_metrics
from ..obs import spans as _spans
from ..utils import faults as _faults
from ..utils.log import Log
from .config import FleetConfig, ServeConfig
from .registry import model_fingerprint


# ----------------------------------------------------------------------
# replica handles
# ----------------------------------------------------------------------
class InprocReplica:
    """A serve stack in this process's threads (unit-test replica)."""

    def __init__(self, booster=None, model_file: Optional[str] = None,
                 config: Optional[ServeConfig] = None):
        self._booster = booster
        self._model_file = model_file
        self._config = config or ServeConfig(port=0, batch_wait_ms=0.5,
                                             timeout_ms=30000)
        self.server = None
        self.httpd = None
        self.url: Optional[str] = None
        self._killed = False

    def start(self) -> str:
        from .http import serve_http
        from .server import Server
        self._config.port = 0
        self.server = Server(config=self._config)
        if self._booster is not None:
            self.server.registry.publish(self._booster)
        elif self._model_file:
            self.server.registry.publish(model_file=self._model_file)
        self.httpd, _ = serve_http(self.server, port=0, background=True)
        self.url = "http://127.0.0.1:%d" % self.httpd.server_address[1]
        return self.url

    def alive(self) -> bool:
        return not self._killed and self.httpd is not None

    def kill(self) -> None:
        """Crash simulation: the socket closes abruptly, in-flight
        connections reset, nothing drains."""
        self._killed = True
        httpd, server = self.httpd, self.server
        self.httpd = None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:              # noqa: BLE001 - already dead
                pass
        if server is not None:
            try:
                server.stop(timeout=1.0)
            except Exception:              # noqa: BLE001
                pass

    def terminate(self, grace_s: float = 10.0) -> None:
        """Graceful: drain admitted work, then close."""
        self._killed = True
        httpd, server = self.httpd, self.server
        self.httpd = None
        if server is not None:
            try:
                server.drain(grace_s)
            except Exception:              # noqa: BLE001
                pass
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:              # noqa: BLE001
                pass


class ProcessReplica:
    """``python -m lightgbm_tpu task=serve`` in a subprocess."""

    def __init__(self, model_file: str, workdir: str, slot: int = 0,
                 params: Optional[Dict[str, Any]] = None,
                 env: Optional[Dict[str, str]] = None,
                 start_timeout_s: float = 120.0):
        self.model_file = str(model_file)
        self.workdir = str(workdir)
        self.slot = int(slot)
        self.params = dict(params or {})
        self.env = dict(env or {})
        self.start_timeout_s = float(start_timeout_s)
        self.proc: Optional[subprocess.Popen] = None
        self.url: Optional[str] = None
        self.log_path = os.path.join(self.workdir,
                                     f"replica_{self.slot}.log")

    def start(self) -> str:
        os.makedirs(self.workdir, exist_ok=True)
        port_file = os.path.join(
            self.workdir, f"replica_{self.slot}_{os.getpid()}.port")
        try:
            os.remove(port_file)
        except OSError:
            pass
        args = {"task": "serve", "input_model": self.model_file,
                "serve_port": "0", "serve_port_file": port_file}
        args.update({str(k): str(v) for k, v in self.params.items()})
        cmd = [sys.executable, "-m", "lightgbm_tpu"] + \
            [f"{k}={v}" for k, v in args.items()]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # propagate the active trace (if a span is open — e.g. the
        # supervisor restarting a replica during a publish) so the
        # replica can mark its boot against it (obs/spans.py)
        env.update(_spans.env_carrier())
        env.update(self.env)
        log = open(self.log_path, "ab")
        try:
            self.proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                         env=env, cwd=self.workdir)
        finally:
            log.close()
        deadline = time.monotonic() + self.start_timeout_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.slot} exited rc={self.proc.returncode}"
                    f" during startup (log: {self.log_path})")
            if os.path.isfile(port_file):
                try:
                    with open(port_file) as f:
                        port = int(f.read().strip())
                    self.url = f"http://127.0.0.1:{port}"
                    return self.url
                except (OSError, ValueError):
                    pass                   # torn read; retry
            time.sleep(0.05)
        self.kill()
        raise RuntimeError(f"replica {self.slot} did not report a port "
                           f"within {self.start_timeout_s:.0f}s "
                           f"(log: {self.log_path})")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    def terminate(self, grace_s: float = 10.0) -> None:
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.terminate()              # SIGTERM -> graceful drain
        try:
            self.proc.wait(timeout=max(grace_s, 0.1))
        except subprocess.TimeoutExpired:
            self.kill()


# ----------------------------------------------------------------------
# supervisor
# ----------------------------------------------------------------------
class _Slot:
    __slots__ = ("index", "handle", "url", "state", "probe_fails",
                 "failures", "next_restart_at", "start_deadline",
                 "opened_at", "in_rotation", "health_model_id",
                 "health_models", "draining")

    def __init__(self, index: int):
        self.index = index
        self.handle = None
        self.url: Optional[str] = None
        self.state = "new"    # new|starting|healthy|backoff|circuit_open
        self.probe_fails = 0
        self.failures = 0     # consecutive, reset on a healthy probe
        self.next_restart_at = 0.0
        self.start_deadline = 0.0
        self.opened_at = 0.0
        self.in_rotation = False
        self.health_model_id: Optional[str] = None
        # per-tenant fingerprints from the last /healthz body (the
        # ``models`` map) — what reconciliation and endpoints() compare
        # against the fleet's desired set
        self.health_models: Dict[str, Optional[str]] = {}
        # the last probe answered 503 {"draining": true}: deliberately
        # finishing admitted work, must not be routed to OR killed
        self.draining = False


class FleetSupervisor:
    """Supervises N replica slots; see the module docstring."""

    def __init__(self, factory: Callable[[int], Any],
                 config: Optional[FleetConfig] = None,
                 recorder=None):
        self.factory = factory
        self.config = config or FleetConfig()
        self.config.validate()
        self.recorder = recorder
        self._slots = [_Slot(i) for i in range(self.config.replicas)]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # desired model state per tenant name: {name: (model_id,
        # model_text)}.  The single-model API (publish_model with no
        # name) lives under the "default" tenant.
        self._desired: Dict[str, tuple] = {}
        # a router fronting this fleet (set_router): its ltpu_router_*
        # registry series join the aggregate scrape
        self._router = None

    # -- telemetry -----------------------------------------------------
    def _emit(self, event: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.emit("fleet", event=event, **fields)

    # -- lifecycle -----------------------------------------------------
    def start(self, wait_healthy_s: float = 0.0) -> "FleetSupervisor":
        for slot in self._slots:
            self._spawn(slot, time.monotonic())
        self._thread = threading.Thread(target=self._monitor,
                                        name="ltpu-fleet", daemon=True)
        self._thread.start()
        if wait_healthy_s > 0:
            deadline = time.monotonic() + wait_healthy_s
            while time.monotonic() < deadline:
                if len(self.endpoints()) == len(self._slots):
                    break
                time.sleep(0.05)
        return self

    def stop(self, grace_s: Optional[float] = None) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        grace = 10.0 if grace_s is None else grace_s
        for slot in self._slots:
            if slot.handle is not None:
                try:
                    slot.handle.terminate(grace)
                except Exception:          # noqa: BLE001
                    pass
                slot.handle = None
            slot.in_rotation = False

    # -- introspection / routing --------------------------------------
    def _routable(self, slot: _Slot) -> bool:
        """Caller holds the lock.  A slot is routable only when its
        last probe was healthy and non-draining AND every desired
        tenant's fingerprint matches the replica's last-reported one —
        so a mid-drain or stale-model replica never reaches clients,
        even in the window between publish_model setting the desired
        state and the per-slot swaps landing."""
        if not (slot.in_rotation and slot.url) or slot.draining:
            return False
        for name, (mid, _text) in self._desired.items():
            if slot.health_models.get(name) != mid:
                return False
        return True

    def endpoints(self) -> List[str]:
        """Base URLs of routable replicas: healthy, not draining, and
        serving every desired tenant's CURRENT fingerprint — so even
        clients that round-robin this list themselves never hit a
        mid-deploy or mid-drain replica."""
        with self._lock:
            return [s.url for s in self._slots if self._routable(s)]

    def slots(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"index": s.index, "state": s.state, "url": s.url,
                     "failures": s.failures,
                     "in_rotation": s.in_rotation,
                     "draining": s.draining,
                     "model_id": s.health_model_id,
                     "models": dict(s.health_models)}
                    for s in self._slots]

    def handle(self, index: int):
        return self._slots[index].handle

    def replica_count(self) -> int:
        with self._lock:
            return len(self._slots)

    # -- elastic capacity (serve/autoscaler.py) ------------------------
    def scale_to(self, n: int, reason: str = "") -> int:
        """Resize the fleet to ``n`` slots.  Growing appends fresh
        slots and spawns them immediately; draining retires the
        highest-index slots with a graceful ``terminate`` (admitted
        work finishes — the drain semantics clients never notice) in a
        background thread so the caller's control loop is not blocked
        on the drain grace.  Returns the new slot count."""
        n = int(n)
        if n < 1:
            raise ValueError("scale_to needs n >= 1")
        now = time.monotonic()
        with self._lock:
            cur = len(self._slots)
            if n == cur:
                return cur
            if n > cur:
                added = [_Slot(i) for i in range(cur, n)]
                self._slots.extend(added)
                removed = []
            else:
                added = []
                removed = self._slots[n:]
                del self._slots[n:]
                for slot in removed:
                    slot.in_rotation = False
        self._emit("scale", direction="grow" if added else "drain",
                   from_replicas=cur, to_replicas=n,
                   reason=str(reason)[:120])
        Log.info("fleet: scale %d -> %d replicas (%s)", cur, n,
                 reason or "operator")
        for slot in added:
            self._spawn(slot, now)
        if removed:
            def _retire(slots=removed):
                for slot in slots:
                    handle = slot.handle
                    slot.handle = None
                    if handle is not None:
                        try:
                            handle.terminate(10.0)
                        except Exception:  # noqa: BLE001
                            pass
            threading.Thread(target=_retire, name="ltpu-fleet-drain",
                             daemon=True).start()
        return n

    def active_models(self, model: str = "default"
                      ) -> Dict[int, Optional[str]]:
        """Last-probed fingerprint of one tenant per healthy slot."""
        with self._lock:
            return {s.index: s.health_models.get(
                        model, s.health_model_id if model == "default"
                        else None)
                    for s in self._slots if s.state == "healthy"}

    def desired_fingerprint(self, model: str = "default"
                            ) -> Optional[str]:
        """The fingerprint the named tenant is converging onto (what a
        router tier filters stale replicas against), or None before
        any publish."""
        with self._lock:
            d = self._desired.get(model)
            return d[0] if d else None

    # -- model state ---------------------------------------------------
    def publish_model(self, model_text: str, source: str = "",
                      model: str = "default") -> str:
        """Set the named tenant's desired model and swap every healthy
        replica now; the monitor re-swaps stragglers and restarted
        replicas until the whole fleet converges."""
        mid = model_fingerprint(model_text)
        with self._lock:
            self._desired[model] = (mid, model_text)
            targets = [(s, s.url) for s in self._slots
                       if s.state == "healthy" and s.url]
        # once _desired is set the publish cannot fail as a whole: a
        # slot whose swap misses here (crash race, transport error) is
        # reconciled by the monitor, so the caller never sees an
        # exception for a model the fleet is already converging onto
        for slot, url in targets:
            try:
                self._swap_slot(slot, model, mid, model_text, url)
            except Exception as exc:       # noqa: BLE001 - reconciled
                Log.warning("fleet: replica %d swap errored: %s",
                            slot.index, exc)
                with self._lock:
                    slot.in_rotation = False
        return mid

    def _swap_slot(self, slot: _Slot, name: str, mid: str, text: str,
                   url: Optional[str] = None) -> bool:
        url = url or slot.url
        if url is None:                    # crashed since being listed
            with self._lock:
                slot.in_rotation = False
            return False
        # the X-Ltpu-Trace carrier makes the replica's swap (and the
        # first request the new version serves) join the publish trace
        path = "/swap" if name == "default" else f"/v1/{name}/swap"
        st, out = _post_json(url, path, {"model_str": text},
                             timeout=60,
                             headers=_spans.http_headers())
        if st == 200 and out.get("model_id") == mid:
            with self._lock:
                slot.health_models[name] = mid
                if name == "default":
                    slot.health_model_id = mid
                slot.in_rotation = slot.state == "healthy"
            return True
        Log.warning("fleet: replica %d swap of %r failed (HTTP %s: %s)",
                    slot.index, name, st,
                    str(out.get("error", ""))[:120])
        with self._lock:
            slot.in_rotation = False       # stale model: out of rotation
        return False

    # -- aggregate telemetry probe ------------------------------------
    def stats_probe(self) -> Dict[str, float]:
        """Aggregate serve rollups across reachable replicas, the
        rollback controller's instrument: cumulative request/bad
        counts (bad = shed + timeout + error; rejected is the fleet
        doing its backpressure job) and the worst per-replica p99."""
        total, bad, p99 = 0, 0, 0.0
        with self._lock:
            urls = [s.url for s in self._slots
                    if s.state == "healthy" and s.url]
        for url in urls:
            try:
                with urllib.request.urlopen(
                        url + "/stats",
                        timeout=self.config.probe_timeout_s) as r:
                    s = json.loads(r.read())
            except Exception:              # noqa: BLE001 - probe only
                continue
            counts = s.get("requests") or {}
            total += sum(int(v) for v in counts.values())
            bad += sum(int(counts.get(k, 0))
                       for k in ("shed", "timeout", "error"))
            p99 = max(p99, float((s.get("latency_ms") or {})
                                 .get("p99", 0.0)))
        return {"requests": float(total), "bad": float(bad),
                "p99_ms": p99}

    # -- fleet-level metrics aggregation -------------------------------
    def set_router(self, router) -> None:
        """Attach the router fronting this fleet: its own
        ``ltpu_router_*`` (and ``ltpu_slo_*``) series join
        :meth:`metrics_text` as a ``replica="router"`` scrape — one
        pane of glass for the whole serve tier."""
        self._router = router

    def metrics_text(self) -> str:
        """One Prometheus exposition for the whole fleet: every
        reachable replica's ``GET /metrics`` scrape re-labeled with
        ``replica="<slot>"`` plus supervisor-level gauges (slot
        states, desired model) — the scrape surface a router tier in
        front of :meth:`endpoints` consumes
        (``docs/Observability.md``).  With :meth:`set_router`, the
        router's own series ride along as ``replica="router"``."""
        with self._lock:
            targets = [(s.index, s.url) for s in self._slots
                       if s.state == "healthy" and s.url]
            states = [(s.index, s.state, s.in_rotation)
                      for s in self._slots]
            desired = dict(self._desired)
            router = self._router
        scrapes = []
        if router is not None:
            try:
                scrapes.append(("router", _filter_families(
                    router.metrics_text(),
                    ("ltpu_router_", "ltpu_slo_"))))
            except Exception:              # noqa: BLE001 - probe only
                pass
        for index, url in targets:
            try:
                with urllib.request.urlopen(
                        url + "/metrics",
                        timeout=self.config.probe_timeout_s) as r:
                    scrapes.append((str(index), r.read().decode()))
            except Exception:              # noqa: BLE001 - probe only
                continue
        lines = [
            "# HELP ltpu_fleet_replicas configured replica slots",
            "# TYPE ltpu_fleet_replicas gauge",
            f"ltpu_fleet_replicas {len(states)}",
            "# HELP ltpu_fleet_in_rotation slots currently routable",
            "# TYPE ltpu_fleet_in_rotation gauge",
            f"ltpu_fleet_in_rotation "
            f"{sum(1 for _, _, rot in states if rot)}",
            "# HELP ltpu_fleet_slot_state per-slot supervisor state "
            "(1 = the labeled state is current)",
            "# TYPE ltpu_fleet_slot_state gauge",
        ]
        for index, state, _rot in states:
            lines.append('ltpu_fleet_slot_state{slot="%d",state="%s"}'
                         ' 1' % (index, state))
        if desired:
            lines += [
                "# HELP ltpu_fleet_desired_model_info desired model "
                "fingerprint per tenant (value always 1)",
                "# TYPE ltpu_fleet_desired_model_info gauge",
            ]
            for name in sorted(desired):
                lines.append(
                    'ltpu_fleet_desired_model_info{model="%s",'
                    'model_id="%s"} 1' % (name, desired[name][0]))
        return "\n".join(lines) + "\n" + _obs_metrics.aggregate(scrapes)

    # -- monitor -------------------------------------------------------
    def _backoff_s(self, slot: _Slot) -> float:
        n = max(slot.failures, 1)
        base = min(self.config.backoff_base_s * (2 ** (n - 1)),
                   self.config.backoff_max_s)
        # deterministic jitter: seeded by (seed, slot, attempt) so a
        # herd of replicas spreads out, yet tests replay exactly
        u = Random(self.config.seed * 1_000_003
                   + slot.index * 1009 + n).random()
        return base * (1.0 + self.config.backoff_jitter * u)

    def _spawn(self, slot: _Slot, now: float) -> None:
        try:
            mode = _faults.fire("fleet.spawn")
            if mode == "fail":
                raise RuntimeError("injected fault (fleet.spawn:fail)")
            handle = self.factory(slot.index)
            url = handle.start()
        except BaseException as exc:       # InjectedFault included
            if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                raise
            Log.warning("fleet: replica %d spawn failed: %s",
                        slot.index, exc)
            self._fail(slot, now, cause=f"spawn: {exc}")
            return
        with self._lock:
            slot.handle = handle
            slot.url = url
            slot.state = "starting"
            slot.probe_fails = 0
            slot.start_deadline = now + max(
                10 * self.config.probe_interval_s, 5.0)
        self._emit("replica_start", slot=slot.index, url=url)
        Log.info("fleet: replica %d up at %s", slot.index, url)

    def _fail(self, slot: _Slot, now: float, cause: str) -> None:
        handle = slot.handle
        with self._lock:
            slot.handle = None
            slot.url = None
            slot.in_rotation = False
            slot.health_model_id = None
            slot.health_models = {}
            slot.draining = False
            slot.failures += 1
            failures = slot.failures
        if handle is not None:
            try:
                handle.kill()
            except Exception:              # noqa: BLE001
                pass
        self._emit("replica_exit", slot=slot.index, cause=cause[:200],
                   failures=failures)
        if failures >= self.config.circuit_failures:
            with self._lock:
                slot.state = "circuit_open"
                slot.opened_at = now
            self._emit("circuit_open", slot=slot.index,
                       failures=failures)
            Log.warning("fleet: replica %d circuit OPEN after %d "
                        "consecutive failures — slot leaves the "
                        "rotation", slot.index, failures)
            return
        backoff = self._backoff_s(slot)
        with self._lock:
            slot.state = "backoff"
            slot.next_restart_at = now + backoff
        self._emit("replica_restart", slot=slot.index, attempt=failures,
                   backoff_ms=round(backoff * 1e3, 1))
        Log.info("fleet: replica %d restart #%d in %.2fs (%s)",
                 slot.index, failures, backoff, cause[:120])

    def _probe(self, url: str):
        try:
            with urllib.request.urlopen(
                    url + "/healthz",
                    timeout=self.config.probe_timeout_s) as r:
                obj = json.loads(r.read())
            return bool(obj.get("ok")), obj
        except urllib.error.HTTPError as e:
            # a non-200 /healthz still carries a body — a draining
            # replica answers 503 {"draining": true}, which _tick must
            # distinguish from a hang
            try:
                return False, json.loads(e.read())
            except Exception:              # noqa: BLE001 - probe only
                return False, None
        except Exception:                  # noqa: BLE001 - probe only
            return False, None

    def _tick(self) -> None:
        now = time.monotonic()
        with self._lock:
            # scale_to may resize the slot list concurrently
            slots = list(self._slots)
        for slot in slots:
            state = slot.state
            if state == "circuit_open":
                cd = self.config.circuit_cooldown_s
                if cd > 0 and now - slot.opened_at >= cd:
                    with self._lock:
                        slot.state = "backoff"
                        slot.next_restart_at = now
                    self._emit("circuit_half_open", slot=slot.index)
                continue
            if state == "backoff":
                if now >= slot.next_restart_at:
                    self._spawn(slot, now)
                continue
            if state not in ("starting", "healthy"):
                continue
            handle, url = slot.handle, slot.url
            if handle is None or url is None:
                continue
            if not handle.alive():
                self._fail(slot, now, cause="process exited")
                continue
            ok, health = self._probe(url)
            if ok:
                body = health or {}
                with self._lock:
                    slot.probe_fails = 0
                    slot.failures = 0
                    slot.state = "healthy"
                    slot.draining = False
                    slot.health_model_id = body.get("model_id")
                    models = body.get("models")
                    slot.health_models = dict(models) \
                        if isinstance(models, dict) else \
                        {"default": body.get("model_id")}
                    stale = [(n, d) for n, d in self._desired.items()
                             if slot.health_models.get(n) != d[0]]
                if stale:
                    # reconcile: restarted/straggler replica still on
                    # an old model (for ANY tenant) rejoins only once
                    # every stale tenant is re-swapped
                    for name, (mid, text) in stale:
                        if not self._swap_slot(slot, name, mid, text):
                            break
                else:
                    with self._lock:
                        slot.in_rotation = True
                continue
            if health is not None and health.get("draining"):
                # graceful drain in progress (operator SIGTERM): the
                # replica is deliberately finishing admitted work.
                # Stop routing to it, but do NOT count probes toward a
                # kill — SIGKILLing it now would drop the very
                # requests the drain protects.  The restart rides the
                # normal process-exit path once the drain completes.
                with self._lock:
                    slot.in_rotation = False
                    slot.draining = True
                    slot.probe_fails = 0
                    slot.health_model_id = None
                    slot.health_models = {}
                continue
            if state == "starting":
                if now > slot.start_deadline:
                    self._fail(slot, now, cause="never became healthy")
                continue
            with self._lock:
                slot.probe_fails += 1
                fails = slot.probe_fails
                slot.in_rotation = False   # failing probes: stop routing
            if fails >= self.config.fail_threshold:
                self._fail(slot, now,
                           cause=f"{fails} consecutive failed probes "
                                 f"(hung?)")

    def _monitor(self) -> None:
        while not self._stop.wait(self.config.probe_interval_s):
            try:
                self._tick()
            except Exception as exc:       # noqa: BLE001 - keep going
                Log.warning("fleet: monitor tick failed: %s", exc)


def _filter_families(text: str, prefixes) -> str:
    """Keep only the metric families whose name starts with one of
    ``prefixes`` from a Prometheus exposition — the router process's
    registry also carries fleet-irrelevant series the aggregate must
    not duplicate."""
    out: List[str] = []
    for line in text.splitlines():
        s = line.strip()
        if not s:
            continue
        if s.startswith("# "):
            parts = s.split(None, 3)
            name = parts[2] if len(parts) >= 3 else ""
        else:
            name = s.split("{", 1)[0].split(None, 1)[0]
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix):
                base = base[: -len(suffix)]
                break
        if any(base.startswith(p) or name.startswith(p)
               for p in prefixes):
            out.append(s)
    return "\n".join(out) + "\n"


def _post_json(url: str, path: str, obj: Dict[str, Any],
               timeout: float = 30.0,
               headers: Optional[Dict[str, str]] = None):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        url + path, data=json.dumps(obj).encode(), headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except ValueError:
            return e.code, {"error": "unparseable body"}
    except (urllib.error.URLError, OSError) as e:
        return 599, {"error": f"transport: {e}"}
