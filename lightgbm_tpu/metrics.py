"""Evaluation metrics.

Capability parity with ``src/metric/`` (factory ``metric.cpp:12-51``).
Metrics evaluate on host numpy (scores come back from device once per
eval round, matching the reference where metrics are computed locally
per machine outside the training hot loop).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

import numpy as np

from .utils.log import Log

_REGISTRY: Dict[str, Type["Metric"]] = {}


def register(*names):
    def deco(cls):
        for n in names:
            _REGISTRY[n] = cls
        cls.name = names[0]
        return cls
    return deco


# objective name -> default metric (metric.cpp behavior: metric defaults
# to the objective's own loss)
_DEFAULT_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l2": "l2", "l2": "l2", "mse": "l2",
    "rmse": "rmse", "l2_root": "rmse",
    "regression_l1": "l1", "l1": "l1", "mae": "l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape", "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss", "softmax": "multi_logloss",
    "multiclassova": "multi_logloss", "ova": "multi_logloss",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "ndcg",
}


def default_metric_for(objective: str) -> str:
    return _DEFAULT_FOR_OBJECTIVE.get(objective, "l2")


def create_metrics(names, config) -> List["Metric"]:
    out = []
    for n in names:
        n = n.strip()
        if not n or n in ("None", "na", "null", "custom"):
            continue
        if n not in _REGISTRY:
            Log.warning("unknown metric %s (skipped)", n)
            continue
        m = _REGISTRY[n](config)
        if not any(type(o) is type(m) for o in out):
            out.append(m)
    return out


class Metric:
    name = "base"
    higher_better = False

    def __init__(self, config):
        self.config = config

    def eval(self, label: np.ndarray, score: np.ndarray,
             weight: Optional[np.ndarray] = None,
             query_boundaries: Optional[np.ndarray] = None) -> float:
        """score is the TRANSFORMED prediction (probability for binary,
        per-class probabilities for multiclass, raw for regression)."""
        raise NotImplementedError

    def _avg(self, values, weight):
        values = np.asarray(values, np.float64)
        if weight is None:
            return float(np.mean(values))
        return float(np.sum(values * weight) / np.sum(weight))


@register("l2", "mean_squared_error", "mse")
class L2Metric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        return self._avg((score - label) ** 2, weight)


@register("rmse", "root_mean_squared_error", "l2_root")
class RMSEMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        return float(np.sqrt(self._avg((score - label) ** 2, weight)))


@register("l1", "mean_absolute_error", "mae", "regression_l1")
class L1Metric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        return self._avg(np.abs(score - label), weight)


@register("binary_logloss", "binary")
class BinaryLoglossMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        p = np.clip(score, 1e-15, 1 - 1e-15)
        loss = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        return self._avg(loss, weight)


@register("binary_error")
class BinaryErrorMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        pred = (score > 0.5).astype(np.float64)
        return self._avg(pred != label, weight)


@register("quantile")
class QuantileMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        alpha = float(self.config.alpha)
        d = label - score
        loss = np.where(d >= 0, alpha * d, (alpha - 1) * d)
        return self._avg(loss, weight)


@register("huber")
class HuberMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        a = float(self.config.alpha)
        d = np.abs(score - label)
        loss = np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))
        return self._avg(loss, weight)


@register("fair")
class FairMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        c = float(self.config.fair_c)
        x = np.abs(score - label)
        loss = c * x - c * c * np.log1p(x / c)
        return self._avg(loss, weight)


@register("poisson")
class PoissonMetric(Metric):
    """Poisson negative log-likelihood (score is the mean)."""
    def eval(self, label, score, weight=None, query_boundaries=None):
        eps = 1e-10
        mu = np.maximum(score, eps)
        loss = mu - label * np.log(mu)
        return self._avg(loss, weight)


@register("mape", "mean_absolute_percentage_error")
class MAPEMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        loss = np.abs(score - label) / np.maximum(1.0, np.abs(label))
        return self._avg(loss, weight)


@register("gamma")
class GammaMetric(Metric):
    """Gamma negative log-likelihood."""
    def eval(self, label, score, weight=None, query_boundaries=None):
        eps = 1e-10
        mu = np.maximum(score, eps)
        loss = label / mu + np.log(mu)
        return self._avg(loss, weight)


@register("gamma_deviance", "gamma-deviance")
class GammaDevianceMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        eps = 1e-10
        r = label / np.maximum(score, eps)
        loss = 2.0 * (np.log(np.maximum(1.0 / np.maximum(r, eps), eps)) +
                      r - 1.0)
        return self._avg(loss, weight)


@register("tweedie")
class TweedieMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        rho = float(self.config.tweedie_variance_power)
        eps = 1e-10
        mu = np.maximum(score, eps)
        a = label * np.power(mu, 1 - rho) / (1 - rho)
        b = np.power(mu, 2 - rho) / (2 - rho)
        return self._avg(-a + b, weight)


@register("multi_logloss", "multiclass", "softmax", "multiclassova",
          "multiclass_ova", "ova", "ovr")
class MultiLoglossMetric(Metric):
    """score: (rows, num_class) probabilities."""
    def eval(self, label, score, weight=None, query_boundaries=None):
        rows = np.arange(len(label))
        p = np.clip(score[rows, label.astype(np.int64)], 1e-15, 1.0)
        return self._avg(-np.log(p), weight)


@register("multi_error")
class MultiErrorMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        k = max(int(self.config.multi_error_top_k), 1)
        if k == 1:
            pred = np.argmax(score, axis=1)
            err = pred != label.astype(np.int64)
        else:
            topk = np.argsort(-score, axis=1)[:, :k]
            err = ~np.any(topk == label.astype(np.int64)[:, None], axis=1)
        return self._avg(err.astype(np.float64), weight)


@register("cross_entropy", "xentropy")
class CrossEntropyMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        p = np.clip(score, 1e-15, 1 - 1e-15)
        loss = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        return self._avg(loss, weight)


@register("cross_entropy_lambda", "xentlambda")
class CrossEntropyLambdaMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        # score is log1p(exp(raw)) = hhat
        hhat = np.maximum(score, 1e-15)
        if weight is None:
            z = 1.0 - np.exp(-hhat)
        else:
            z = 1.0 - np.exp(-weight * hhat)
        z = np.clip(z, 1e-15, 1 - 1e-15)
        loss = -(label * np.log(z) + (1 - label) * np.log(1 - z))
        return float(np.mean(loss))


@register("kldiv", "kullback_leibler")
class KLDivMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        p = np.clip(score, 1e-15, 1 - 1e-15)
        y = np.clip(label, 0.0, 1.0)

        def xlogx(x):
            return np.where(x > 0, x * np.log(np.maximum(x, 1e-15)), 0.0)
        kl = (xlogx(y) + xlogx(1 - y) -
              (y * np.log(p) + (1 - y) * np.log(1 - p)))
        return self._avg(kl, weight)


class _RankMetric(Metric):
    higher_better = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = [int(k) for k in (config.eval_at or [1, 2, 3, 4, 5])]
        from .objectives import default_label_gain
        gains = config.label_gain
        self.label_gain = (np.asarray(gains, np.float64) if gains
                           else default_label_gain())


@register("ndcg", "lambdarank")
class NDCGMetric(_RankMetric):
    """NDCG at the first ``eval_at`` position (all positions are reported
    by the engine via ``eval_all``)."""

    def eval(self, label, score, weight=None, query_boundaries=None):
        return self.eval_all(label, score, weight, query_boundaries)[0][1]

    def eval_all(self, label, score, weight=None, query_boundaries=None):
        if query_boundaries is None:
            raise ValueError("ndcg requires query boundaries")
        out = []
        for k in self.eval_at:
            ndcgs = []
            ws = []
            for q in range(len(query_boundaries) - 1):
                lo, hi = query_boundaries[q], query_boundaries[q + 1]
                lab = label[lo:hi].astype(np.int64)
                sc = score[lo:hi]
                g = self.label_gain[lab]
                if g.sum() <= 0:
                    ndcgs.append(1.0)  # no relevant docs counts as 1
                else:
                    order = np.argsort(-sc, kind="stable")
                    top = g[order[:k]]
                    dcg = np.sum(top / np.log2(np.arange(len(top)) + 2.0))
                    ideal = np.sort(g)[::-1][:k]
                    idcg = np.sum(ideal /
                                  np.log2(np.arange(len(ideal)) + 2.0))
                    ndcgs.append(dcg / idcg)
                ws.append(weight[lo] if weight is not None else 1.0)
            ndcgs = np.asarray(ndcgs)
            ws = np.asarray(ws)
            out.append((f"ndcg@{k}", float(np.sum(ndcgs * ws) / np.sum(ws))))
        return out


@register("map", "mean_average_precision")
class MAPMetric(_RankMetric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        return self.eval_all(label, score, weight, query_boundaries)[0][1]

    def eval_all(self, label, score, weight=None, query_boundaries=None):
        if query_boundaries is None:
            raise ValueError("map requires query boundaries")
        out = []
        for k in self.eval_at:
            maps = []
            ws = []
            for q in range(len(query_boundaries) - 1):
                lo, hi = query_boundaries[q], query_boundaries[q + 1]
                rel = (label[lo:hi] > 0).astype(np.float64)
                sc = score[lo:hi]
                order = np.argsort(-sc, kind="stable")
                r = rel[order[:k]]
                hits = np.cumsum(r)
                denom = np.arange(1, len(r) + 1)
                n_rel = min(int(rel.sum()), k) or 1
                ap = np.sum(r * hits / denom) / n_rel if rel.sum() > 0 else 0.0
                maps.append(ap)
                ws.append(weight[lo] if weight is not None else 1.0)
            maps = np.asarray(maps)
            ws = np.asarray(ws)
            out.append((f"map@{k}", float(np.sum(maps * ws) / np.sum(ws))))
        return out


@register("auc")
class AUCMetric(Metric):
    """ROC AUC by rank-sum over sorted scores with tie handling
    (``binary_metric.hpp`` AUCMetric)."""
    higher_better = True

    def eval(self, label, score, weight=None, query_boundaries=None):
        if weight is None:
            weight = np.ones_like(label, dtype=np.float64)
        order = np.argsort(score, kind="mergesort")
        s, y, w = score[order], label[order], weight[order]
        pos = np.sum(w * (y > 0))
        neg = np.sum(w) - pos
        if pos <= 0 or neg <= 0:
            return 1.0
        # per unique score: area += tie_pos * (neg_below + tie_neg / 2)
        starts = np.concatenate([[0], np.nonzero(np.diff(s))[0] + 1])
        wp = np.where(y > 0, w, 0.0)
        wn = np.where(y > 0, 0.0, w)
        tie_pos = np.add.reduceat(wp, starts)
        tie_neg = np.add.reduceat(wn, starts)
        neg_below = np.cumsum(tie_neg) - tie_neg
        area = np.sum(tie_pos * (neg_below + tie_neg / 2.0))
        return float(area / (pos * neg))
