"""Evaluation metrics.

Capability parity with ``src/metric/`` (factory ``metric.cpp:12-51``).
Metrics evaluate on host numpy (scores come back from device once per
eval round, matching the reference where metrics are computed locally
per machine outside the training hot loop).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

import numpy as np

from .utils.log import Log

_REGISTRY: Dict[str, Type["Metric"]] = {}


def register(*names):
    def deco(cls):
        for n in names:
            _REGISTRY[n] = cls
        cls.name = names[0]
        return cls
    return deco


# objective name -> default metric (metric.cpp behavior: metric defaults
# to the objective's own loss)
_DEFAULT_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l2": "l2", "l2": "l2", "mse": "l2",
    "rmse": "rmse", "l2_root": "rmse",
    "regression_l1": "l1", "l1": "l1", "mae": "l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape", "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss", "softmax": "multi_logloss",
    "multiclassova": "multi_logloss", "ova": "multi_logloss",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "ndcg",
}


def default_metric_for(objective: str) -> str:
    return _DEFAULT_FOR_OBJECTIVE.get(objective, "l2")


def create_metrics(names, config) -> List["Metric"]:
    out = []
    for n in names:
        n = n.strip()
        if not n or n in ("None", "na", "null", "custom"):
            continue
        if n not in _REGISTRY:
            Log.warning("unknown metric %s (skipped)", n)
            continue
        m = _REGISTRY[n](config)
        if not any(type(o) is type(m) for o in out):
            out.append(m)
    return out


class Metric:
    name = "base"
    higher_better = False

    def __init__(self, config):
        self.config = config

    def eval(self, label: np.ndarray, score: np.ndarray,
             weight: Optional[np.ndarray] = None,
             query_boundaries: Optional[np.ndarray] = None) -> float:
        """score is the TRANSFORMED prediction (probability for binary,
        per-class probabilities for multiclass, raw for regression)."""
        raise NotImplementedError

    def _avg(self, values, weight):
        values = np.asarray(values, np.float64)
        if weight is None:
            return float(np.mean(values))
        return float(np.sum(values * weight) / np.sum(weight))


@register("l2", "mean_squared_error", "mse")
class L2Metric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        return self._avg((score - label) ** 2, weight)


@register("rmse", "root_mean_squared_error", "l2_root")
class RMSEMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        return float(np.sqrt(self._avg((score - label) ** 2, weight)))


@register("l1", "mean_absolute_error", "mae", "regression_l1")
class L1Metric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        return self._avg(np.abs(score - label), weight)


@register("binary_logloss", "binary")
class BinaryLoglossMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        p = np.clip(score, 1e-15, 1 - 1e-15)
        loss = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        return self._avg(loss, weight)


@register("binary_error")
class BinaryErrorMetric(Metric):
    def eval(self, label, score, weight=None, query_boundaries=None):
        pred = (score > 0.5).astype(np.float64)
        return self._avg(pred != label, weight)


@register("auc")
class AUCMetric(Metric):
    """ROC AUC by rank-sum over sorted scores with tie handling
    (``binary_metric.hpp`` AUCMetric)."""
    higher_better = True

    def eval(self, label, score, weight=None, query_boundaries=None):
        if weight is None:
            weight = np.ones_like(label, dtype=np.float64)
        order = np.argsort(score, kind="mergesort")
        s, y, w = score[order], label[order], weight[order]
        pos = np.sum(w * (y > 0))
        neg = np.sum(w) - pos
        if pos <= 0 or neg <= 0:
            return 1.0
        # per unique score: area += tie_pos * (neg_below + tie_neg / 2)
        starts = np.concatenate([[0], np.nonzero(np.diff(s))[0] + 1])
        wp = np.where(y > 0, w, 0.0)
        wn = np.where(y > 0, 0.0, w)
        tie_pos = np.add.reduceat(wp, starts)
        tie_neg = np.add.reduceat(wn, starts)
        neg_below = np.cumsum(tie_neg) - tie_neg
        area = np.sum(tie_pos * (neg_below + tie_neg / 2.0))
        return float(area / (pos * neg))
