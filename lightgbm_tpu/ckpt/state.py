"""Bit-exact training-state (de)serialization for checkpoints.

The numbers that make resume *bit-exact* rather than approximate never
go through a text format: tree tables, the f32 score carry, f64 valid
scores and the Mersenne-Twister key vectors are stored as raw numpy
arrays in one ``state.npz`` blob.  Rebuilding a :class:`Tree` from its
arrays restores EVERY field the training paths read (``threshold_bin``
for device replay, ``leaf_count`` for two-column count restoration,
``shrinkage`` for DART reweighting) — the model-text round trip, by
contrast, renders ``split_gain``/``internal_value``/``shrinkage`` at
``%g`` and recovers ``threshold_bin`` by casting, which is fine for a
servable model but not for a continuation that must equal the
uninterrupted run to the last bit.  A ``model.txt`` in the reference
format still rides along in every checkpoint for serving and
inspection (``serve.ModelRegistry.publish_from_checkpoint``).

Host-RNG states (feature-fraction draws, DART drops) are captured as
``numpy.random.RandomState.get_state()`` tuples: the (624,) uint32 key
vector goes into the npz, position/gauss scalars into the JSON meta.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ..models.tree import Tree

__all__ = ["pack_trees", "unpack_trees", "snapshot_to_blobs",
           "blobs_to_snapshot", "rng_state_split", "rng_state_join"]

# Tree fields stored per internal node / per leaf, trimmed to the
# tree's live node count (entries past num_leaves are construction
# zeros, pinned by the round-trip test)
_INNER_FIELDS = ("split_feature", "split_gain", "threshold",
                 "threshold_bin", "decision_type", "left_child",
                 "right_child", "internal_value", "internal_weight",
                 "internal_count")
_LEAF_FIELDS = ("leaf_value", "leaf_weight", "leaf_count",
                "leaf_parent", "leaf_depth")


def pack_trees(models: List[Tree]) -> Dict[str, np.ndarray]:
    """Concatenated struct-of-arrays layout for a tree list."""
    T = len(models)
    out: Dict[str, np.ndarray] = {
        "tree_num_leaves": np.asarray(
            [t.num_leaves for t in models], np.int32),
        "tree_max_leaves": np.asarray(
            [t.max_leaves for t in models], np.int32),
        "tree_num_cat": np.asarray([t.num_cat for t in models], np.int32),
        "tree_shrinkage": np.asarray(
            [t.shrinkage for t in models], np.float64),
    }
    for name in _INNER_FIELDS:
        parts = [getattr(t, name)[:max(t.num_leaves - 1, 0)]
                 for t in models]
        out["tree_" + name] = np.concatenate(parts) if parts else \
            np.zeros(0)
    for name in _LEAF_FIELDS:
        parts = [getattr(t, name)[:t.num_leaves] for t in models]
        out["tree_" + name] = np.concatenate(parts) if parts else \
            np.zeros(0)
    cb = [np.asarray(t.cat_boundaries, np.int64) for t in models]
    ct = [np.asarray(t.cat_threshold, np.int64) for t in models]
    out["tree_cat_boundaries"] = np.concatenate(cb) if T else \
        np.zeros(0, np.int64)
    out["tree_cat_boundaries_len"] = np.asarray(
        [len(x) for x in cb], np.int64)
    out["tree_cat_threshold"] = np.concatenate(ct) if T else \
        np.zeros(0, np.int64)
    out["tree_cat_threshold_len"] = np.asarray(
        [len(x) for x in ct], np.int64)
    return out


def unpack_trees(d: Dict[str, np.ndarray]) -> List[Tree]:
    nl = np.asarray(d["tree_num_leaves"], np.int32)
    ml = np.asarray(d["tree_max_leaves"], np.int32)
    nc = np.asarray(d["tree_num_cat"], np.int32)
    sh = np.asarray(d["tree_shrinkage"], np.float64)
    inner_off = np.concatenate(
        [[0], np.cumsum(np.maximum(nl - 1, 0))]).astype(np.int64)
    leaf_off = np.concatenate([[0], np.cumsum(nl)]).astype(np.int64)
    cb_off = np.concatenate(
        [[0], np.cumsum(d["tree_cat_boundaries_len"])]).astype(np.int64)
    ct_off = np.concatenate(
        [[0], np.cumsum(d["tree_cat_threshold_len"])]).astype(np.int64)
    models: List[Tree] = []
    for i in range(len(nl)):
        t = Tree(int(ml[i]))
        t.num_leaves = int(nl[i])
        t.num_cat = int(nc[i])
        t.shrinkage = float(sh[i])
        i0, i1 = inner_off[i], inner_off[i + 1]
        for name in _INNER_FIELDS:
            dst = getattr(t, name)
            dst[:i1 - i0] = np.asarray(d["tree_" + name][i0:i1],
                                       dst.dtype)
        l0, l1 = leaf_off[i], leaf_off[i + 1]
        for name in _LEAF_FIELDS:
            dst = getattr(t, name)
            dst[:l1 - l0] = np.asarray(d["tree_" + name][l0:l1],
                                       dst.dtype)
        t.cat_boundaries = [int(x) for x in
                            d["tree_cat_boundaries"][cb_off[i]:cb_off[i + 1]]]
        t.cat_threshold = [int(x) for x in
                           d["tree_cat_threshold"][ct_off[i]:ct_off[i + 1]]]
        if not t.cat_boundaries:
            t.cat_boundaries = [0]
        models.append(t)
    return models


# ----------------------------------------------------------------------
# host RNG state <-> (json scalars, npz key vector)
# ----------------------------------------------------------------------
def rng_state_split(state: Tuple) -> Tuple[Dict[str, Any], np.ndarray]:
    """``RandomState.get_state()`` -> (json-able scalars, key array)."""
    algo, keys, pos, has_gauss, cached = state
    return ({"algo": str(algo), "pos": int(pos),
             "has_gauss": int(has_gauss), "cached_gaussian": float(cached)},
            np.asarray(keys, np.uint32))


def rng_state_join(meta: Dict[str, Any], keys: np.ndarray) -> Tuple:
    return (meta["algo"], np.asarray(keys, np.uint32), int(meta["pos"]),
            int(meta["has_gauss"]), float(meta["cached_gaussian"]))


# ----------------------------------------------------------------------
# snapshot dict (GBDT.training_snapshot) <-> (npz arrays, json meta)
# ----------------------------------------------------------------------
def snapshot_to_blobs(snap: Dict[str, Any]
                      ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    arrays: Dict[str, np.ndarray] = {}
    meta: Dict[str, Any] = {
        "iter": int(snap["iter"]),
        "trees_dispatched": int(snap["trees_dispatched"]),
        "shrinkage_rate": float(snap["shrinkage_rate"]),
        "stopped": bool(snap.get("stopped", False)),
        "n_models": len(snap["models"]),
    }
    arrays.update(pack_trees(snap["models"]))
    arrays["score"] = np.asarray(snap["score"], np.float32)
    rng_meta, rng_keys = rng_state_split(snap["rng_feature"])
    meta["rng_feature"] = rng_meta
    arrays["rng_feature_keys"] = rng_keys
    meta["valid_names"] = sorted(snap.get("valid_scores", {}))
    for name in meta["valid_names"]:
        arrays["valid_score__" + name] = np.asarray(
            snap["valid_scores"][name], np.float64)
    extra = dict(snap.get("extra") or {})
    if "rng_drop" in extra:    # DART drop RNG
        drop_meta, drop_keys = rng_state_split(extra.pop("rng_drop"))
        meta["rng_drop"] = drop_meta
        arrays["rng_drop_keys"] = drop_keys
    if "tree_weight" in extra:
        arrays["dart_tree_weight"] = np.asarray(
            extra.pop("tree_weight"), np.float64)
    meta["extra"] = extra      # remaining json-able scalars
    return arrays, meta


def blobs_to_snapshot(arrays: Dict[str, np.ndarray],
                      meta: Dict[str, Any]) -> Dict[str, Any]:
    snap: Dict[str, Any] = {
        "iter": int(meta["iter"]),
        "trees_dispatched": int(meta["trees_dispatched"]),
        "shrinkage_rate": float(meta["shrinkage_rate"]),
        "stopped": bool(meta.get("stopped", False)),
        "models": unpack_trees(arrays),
        "score": np.asarray(arrays["score"], np.float32),
        "rng_feature": rng_state_join(meta["rng_feature"],
                                      arrays["rng_feature_keys"]),
        "valid_scores": {name: np.asarray(arrays["valid_score__" + name],
                                          np.float64)
                         for name in meta.get("valid_names", [])},
    }
    extra = dict(meta.get("extra") or {})
    if "rng_drop" in meta:
        extra["rng_drop"] = rng_state_join(meta["rng_drop"],
                                           arrays["rng_drop_keys"])
    if "dart_tree_weight" in arrays:
        extra["tree_weight"] = [float(x)
                                for x in arrays["dart_tree_weight"]]
    snap["extra"] = extra
    return snap
