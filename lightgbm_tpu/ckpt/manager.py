"""Atomic, schema-versioned, content-hashed training checkpoints.

Directory layout (one directory per checkpoint under the checkpoint
root, named by the iteration it is aligned to)::

    <checkpoint_dir>/
      ckpt_00000040/
        state.npz       # bit-exact training state (ckpt/state.py)
        model.txt       # reference-format model text (serving, CLI)
        extra.json      # RNG scalars, eval history, best-score state
        manifest.json   # written LAST: schema + blob sizes + sha256
      ckpt_00000080/ ...
      .tmp_*            # torn writes land here; loaders ignore them

Write protocol: every blob is written into a ``.tmp_*`` staging
directory and fsynced; the manifest — the checkpoint's commit record —
is written last; then ONE ``os.replace`` publishes the directory and
the parent is fsynced.  A crash at any point leaves either no new
directory or a complete one, never a half-checkpoint under a final
name.

Read protocol: candidates are scanned newest-first; a candidate is
accepted only if its manifest parses, carries the supported schema,
and every blob matches its manifested size AND sha256.  Anything else
(truncated manifest, torn blob, bit rot) is rejected with a telemetry
``checkpoint``/``fallback`` record and the scan falls back to the next
older snapshot — the acceptance criterion "an injected mid-write crash
never leaves an unloadable checkpoint directory".

Retention: ``keep_last_n`` newest VALID checkpoints survive each save;
older ones (and stale staging directories) are pruned.
"""
from __future__ import annotations

import io
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import spans as _spans
from ..utils.log import Log
from ..utils import telemetry as _telemetry
from . import atomic

__all__ = ["CheckpointError", "CheckpointManager", "SCHEMA_VERSION"]

SCHEMA_VERSION = 1
_MANIFEST = "manifest.json"
_STATE = "state.npz"
_MODEL = "model.txt"
_EXTRA = "extra.json"
_NAME_RE = re.compile(r"^ckpt_(\d{8})$")


class CheckpointError(Exception):
    """A checkpoint directory failed validation or restore."""


def _fsync_write(path: str, data: bytes) -> int:
    """Plain write + fsync (inside a staging dir — the atomicity comes
    from the directory rename, not per-file renames)."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    return len(data)


class CheckpointManager:
    """Writes/loads training checkpoints under one root directory."""

    def __init__(self, directory: str, keep_last_n: int = 2,
                 recorder=None):
        self.directory = str(directory)
        self.keep_last_n = max(int(keep_last_n), 1)
        self.recorder = recorder
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _emit(self, event: str, **fields) -> None:
        _telemetry.counters.incr(f"ckpt_{event}s")
        rec = self.recorder or _telemetry.get_recorder()
        if rec is not None:
            fields.setdefault("duration_ms", 0.0)
            rec.emit("checkpoint", event=event, **fields)

    # ------------------------------------------------------------------
    # discovery / validation
    # ------------------------------------------------------------------
    @staticmethod
    def is_checkpoint_dir(path: str) -> bool:
        return os.path.isfile(os.path.join(path, _MANIFEST))

    def candidates(self) -> List[Tuple[int, str]]:
        """(iteration, path) of finalized checkpoints, oldest first."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = _NAME_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        return sorted(out)

    @staticmethod
    def validate(path: str) -> List[str]:
        """Problems with one checkpoint directory (empty = valid)."""
        mpath = os.path.join(path, _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except OSError as exc:
            return [f"manifest unreadable: {exc}"]
        except ValueError as exc:
            return [f"manifest corrupt/truncated: {exc}"]
        errs: List[str] = []
        if not isinstance(manifest, dict):
            return ["manifest is not a JSON object"]
        if manifest.get("schema") != SCHEMA_VERSION:
            errs.append(f"unsupported schema {manifest.get('schema')!r}")
        blobs = manifest.get("blobs")
        if not isinstance(blobs, dict) or not blobs:
            return errs + ["manifest lists no blobs"]
        for name, info in blobs.items():
            bpath = os.path.join(path, name)
            if not os.path.isfile(bpath):
                errs.append(f"blob {name} missing")
                continue
            size = os.path.getsize(bpath)
            if size != int(info.get("bytes", -1)):
                errs.append(f"blob {name} truncated: {size} bytes vs "
                            f"{info.get('bytes')} manifested")
                continue
            digest = atomic.sha256_file(bpath)
            if digest != info.get("sha256"):
                errs.append(f"blob {name} content hash mismatch")
        return errs

    # ------------------------------------------------------------------
    # save
    # ------------------------------------------------------------------
    def save(self, booster, reason: str = "periodic",
             eval_history: Optional[List] = None) -> str:
        """Write one checkpoint aligned to the booster's last COMPLETED
        iteration (mid-fused-block state is aligned by the snapshot
        capture, WITHOUT disturbing the block being served or any
        async-pipelined blocks still in flight — training keeps
        serving from them after the save; restore is what discards
        the queue).  Returns the finalized checkpoint path."""
        from . import state as state_mod
        t0 = time.perf_counter()
        fault = atomic.fault_armed()
        snap = booster._gbdt.training_snapshot()
        arrays, meta = state_mod.snapshot_to_blobs(snap)
        iteration = int(meta["iter"])
        g = booster._gbdt
        meta.update({
            "schema": SCHEMA_VERSION,
            "reason": str(reason),
            "created": round(time.time(), 3),
            "num_class": int(g.num_class),
            "num_tree_per_iteration": int(g.num_tree_per_iteration),
            "num_data": int(g.num_data) if g.train_set is not None else 0,
            "objective": str(g.config.objective),
            "boosting": str(g.config.boosting),
            "best_iteration": int(booster.best_iteration),
            "best_score": booster.best_score,
            "eval_history": eval_history or [],
            # mesh topology the snapshot was taken under: resume on a
            # different device set validates against this and
            # RE-SHARDS (the training state is host-side and mesh-
            # agnostic) instead of failing inside shard_map
            "mesh": (g.mesh_identity() if hasattr(g, "mesh_identity")
                     else {"learner": "serial", "num_shards": 1,
                           "mesh_shape": [1]}),
        })
        # streamed-ingest cache identity (io/stream.py): resume must
        # find the SAME binned cache and reuse it — a restore that
        # re-binned is a MED anomaly (docs/Streaming.md)
        stream_id = g.stream_identity() \
            if hasattr(g, "stream_identity") else None
        if stream_id is not None:
            meta["stream"] = stream_id
        # device-block pager geometry (io/pager.py): provenance that
        # this snapshot came from an out-of-core run — paged training
        # is byte-identical to resident, so resume may use ANY page
        # geometry (or none); the record is for triage, not a check
        pager_id = g.pager_identity() \
            if hasattr(g, "pager_identity") else None
        if pager_id is not None:
            meta["pager"] = pager_id
        # trace carrier (obs/spans.py): a watcher in ANOTHER process
        # re-enters this context, so the saving run's trace continues
        # through validate -> canary -> publish -> first served request
        trace = _spans.format_carrier()
        if trace:
            meta["trace"] = trace
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        state_bytes = buf.getvalue()
        model_text = booster.model_to_string(num_iteration=-1)
        extra_bytes = json.dumps(meta, sort_keys=True).encode("utf-8")

        final = os.path.join(self.directory, f"ckpt_{iteration:08d}")
        # staging is per (pid, thread): the continual daemon's stall
        # watchdog can leave an abandoned attempt racing its retry in
        # the SAME process at the same boundary — a pid-only name
        # would let one writer rmtree the other's half-written staging
        staging = os.path.join(
            self.directory,
            f".tmp_ckpt_{iteration:08d}_{os.getpid()}"
            f"_{threading.get_ident()}")
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)
        blobs: Dict[str, Dict[str, Any]] = {}
        for name, data in ((_STATE, state_bytes),
                           (_MODEL, model_text.encode("utf-8")),
                           (_EXTRA, extra_bytes)):
            bpath = os.path.join(staging, name)
            atomic.consume_fault(fault, "blob", bpath)
            n = _fsync_write(bpath, data)
            blobs[name] = {"bytes": n, "sha256": atomic.sha256_file(bpath)}
        atomic.consume_fault(fault, "manifest",
                             os.path.join(staging, _MANIFEST))
        manifest = {"schema": SCHEMA_VERSION, "iteration": iteration,
                    "reason": str(reason), "created": meta["created"],
                    "mesh": meta["mesh"], "blobs": blobs}
        if "stream" in meta:
            manifest["stream"] = meta["stream"]
        if "pager" in meta:
            manifest["pager"] = meta["pager"]
        _fsync_write(os.path.join(staging, _MANIFEST),
                     json.dumps(manifest, sort_keys=True,
                                indent=1).encode("utf-8"))
        # the staging DIRECTORY's entries must be durable before the
        # publishing rename, or a power loss can surface a final-named
        # dir with missing blob entries
        atomic.fsync_dir(staging)
        if os.path.isdir(final):
            # a re-save of the same boundary (resume overlap): the new
            # bytes win; the brief .old window is covered by the OTHER
            # retained checkpoints
            old = final + ".old"
            if os.path.isdir(old):
                shutil.rmtree(old)
            os.replace(final, old)
            os.replace(staging, final)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.replace(staging, final)
        atomic.fsync_dir(self.directory)
        atomic.consume_fault(fault, "post_finalize",
                             os.path.join(final, _STATE))
        self._retain(keep=final)
        total = sum(b["bytes"] for b in blobs.values())
        dur = (time.perf_counter() - t0) * 1e3
        _telemetry.counters.incr("ckpt_save_bytes", total)
        self._emit("save", duration_ms=round(dur, 3), iter=iteration,
                   reason=str(reason), bytes=total,
                   path=os.path.basename(final))
        Log.info("checkpoint: saved iteration %d (%s, %.1f KB, %.0f ms)"
                 " -> %s", iteration, reason, total / 1e3, dur, final)
        return final

    def _retain(self, keep: str) -> None:
        cands = self.candidates()
        if len(cands) > self.keep_last_n:
            for _, path in cands[:-self.keep_last_n]:
                if os.path.abspath(path) != os.path.abspath(keep):
                    shutil.rmtree(path, ignore_errors=True)
        # stale staging dirs from crashed writers, and .old dirs a
        # crash mid re-save-swap left behind
        for name in os.listdir(self.directory):
            if name.startswith(".tmp_ckpt_") or \
                    (name.startswith("ckpt_") and name.endswith(".old")):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def prune_after(self, iteration: int) -> List[str]:
        """Delete finalized checkpoints NEWER than ``iteration`` — the
        continual daemon's exact-rewind primitive: when a batch is
        quarantined mid-train (non-finite guard, exhausted retries),
        its in-flight snapshots must leave the lineage, or a restarted
        daemon would resume from state the surviving batches never
        produced.  Returns the pruned paths."""
        pruned = []
        for iter_, path in self.candidates():
            if iter_ > int(iteration):
                shutil.rmtree(path, ignore_errors=True)
                pruned.append(path)
                self._emit("prune", iter=iter_,
                           path=os.path.basename(path))
        if pruned:
            Log.info("checkpoint: pruned %d snapshot(s) past iteration "
                     "%d (quarantined-batch rewind)", len(pruned),
                     iteration)
        return pruned

    # ------------------------------------------------------------------
    # load
    # ------------------------------------------------------------------
    def load_dir(self, path: str) -> Dict[str, Any]:
        """Validate + parse ONE checkpoint directory into
        ``{"path", "meta", "snapshot"}``; raises :class:`CheckpointError`
        on any validation failure."""
        from . import state as state_mod
        t0 = time.perf_counter()
        errs = self.validate(path)
        if errs:
            raise CheckpointError(f"{path}: " + "; ".join(errs))
        with np.load(os.path.join(path, _STATE)) as z:
            arrays = {k: z[k] for k in z.files}
        with open(os.path.join(path, _EXTRA)) as f:
            meta = json.load(f)
        snap = state_mod.blobs_to_snapshot(arrays, meta)
        dur = (time.perf_counter() - t0) * 1e3
        self._emit("load", duration_ms=round(dur, 3),
                   iter=int(meta.get("iter", -1)),
                   bytes=int(os.path.getsize(os.path.join(path, _STATE))),
                   path=os.path.basename(path))
        return {"path": path, "meta": meta, "snapshot": snap}

    def newest_valid(self) -> Optional[str]:
        """Path of the newest manifest-valid checkpoint, emitting a
        ``fallback`` record (with the real validation errors) for
        every rejected newer candidate — the validation-only half of
        :meth:`load_latest`, shared with the serving tier."""
        for _, path in reversed(self.candidates()):
            errs = self.validate(path)
            if not errs:
                return path
            Log.warning("checkpoint: %s: %s — falling back to the "
                        "previous snapshot", path, "; ".join(errs))
            self._emit("fallback", path=os.path.basename(path),
                       error="; ".join(errs)[:300])
        return None

    def load_latest(self) -> Optional[Dict[str, Any]]:
        """Newest valid checkpoint, falling back past corrupt/truncated
        candidates (each rejection emits a ``fallback`` record)."""
        path = self.newest_valid()
        return self.load_dir(path) if path is not None else None

    def resolve(self, target: str) -> Optional[Dict[str, Any]]:
        """Load ``target``: a finalized checkpoint directory (strict —
        corruption raises), a checkpoint root (newest valid wins, with
        fallback), or ``auto``/``latest`` (this manager's root)."""
        if target in ("auto", "latest", ""):
            return self.load_latest()
        if self.is_checkpoint_dir(target):
            return self.load_dir(target)
        if os.path.isdir(target):
            return CheckpointManager(target, self.keep_last_n,
                                     self.recorder).load_latest()
        raise CheckpointError(f"resume_from={target!r}: no such "
                              f"checkpoint directory")

    # ------------------------------------------------------------------
    # restore
    # ------------------------------------------------------------------
    def restore(self, booster, loaded: Dict[str, Any]) -> int:
        """Install a loaded checkpoint into a freshly-constructed
        booster (valid sets must already be registered — their scores
        are overwritten from the snapshot).  Returns the iteration to
        resume from."""
        meta = loaded["meta"]
        g = booster._gbdt
        if int(meta["num_tree_per_iteration"]) != g.num_tree_per_iteration:
            Log.fatal("checkpoint has num_tree_per_iteration=%s, "
                      "booster has %d", meta["num_tree_per_iteration"],
                      g.num_tree_per_iteration)
        if meta.get("num_data") and int(meta["num_data"]) != g.num_data:
            Log.fatal("checkpoint was taken on %s training rows, the "
                      "current dataset has %d — resume needs the same "
                      "training data", meta["num_data"], g.num_data)
        alias = {"gbrt": "gbdt", "random_forest": "rf"}
        ck_boost = alias.get(meta.get("boosting", "gbdt"),
                             meta.get("boosting", "gbdt"))
        cur_boost = alias.get(g.config.boosting, g.config.boosting)
        if meta.get("boosting") is not None and ck_boost != cur_boost:
            # a DART checkpoint restored into a plain-GBDT booster
            # would silently drop the drop-RNG/weight state and stop
            # renormalizing — wrong model, no error
            Log.fatal("checkpoint was taken with boosting=%s, the "
                      "booster is configured with boosting=%s",
                      meta.get("boosting"), g.config.boosting)
        if meta.get("objective") != g.config.objective:
            Log.warning("checkpoint objective %r differs from configured "
                        "%r", meta.get("objective"), g.config.objective)
        ck_mesh = meta.get("mesh") or {}
        if ck_mesh and hasattr(g, "mesh_identity"):
            cur = g.mesh_identity()
            ck_kind = str(ck_mesh.get("learner", cur["learner"]))
            ck_shards = int(ck_mesh.get("num_shards",
                                        cur["num_shards"]) or 1)
            ck_shape = [int(s) for s in
                        ck_mesh.get("mesh_shape",
                                    cur["mesh_shape"]) or [1]]
            cur_shape = [int(s) for s in cur["mesh_shape"] or [1]]
            if (ck_kind, ck_shards, ck_shape) != (cur["learner"],
                                                  cur["num_shards"],
                                                  cur_shape):
                # cross-mesh resume — a different width, a different
                # 2-D shape (a 4x2 data2d checkpoint into a 2x4
                # booster has EQUAL shard counts), or a different
                # learner: the checkpointed state is host-side and
                # mesh-agnostic — the freshly constructed booster
                # already placed its tensors under ITS shardings, so
                # restoring here IS the re-shard.  Continuation is
                # bit-exact at the new topology (docs/Distributed.md
                # parity contract).
                Log.warning(
                    "checkpoint was taken under tree_learner=%s on a "
                    "%d-shard mesh %s; this booster runs "
                    "tree_learner=%s over %d shard(s) %s — "
                    "re-sharding the restored training state "
                    "(bit-exact continuation on the new topology; "
                    "see docs/Distributed.md)",
                    ck_kind, ck_shards, "x".join(map(str, ck_shape)),
                    cur["learner"], cur["num_shards"],
                    "x".join(map(str, cur_shape)))
                _telemetry.counters.incr("recovery_reshards")
                rec = self.recorder or _telemetry.get_recorder() or \
                    getattr(g, "_telemetry", None)
                if rec is not None:
                    rec.emit("recovery", event="reshard",
                             from_shards=ck_shards,
                             to_shards=int(cur["num_shards"]),
                             from_learner=ck_kind,
                             to_learner=cur["learner"],
                             from_shape=ck_shape,
                             to_shape=cur_shape,
                             iter=int(meta.get("iter", -1)))
        ck_stream = meta.get("stream")
        if ck_stream:
            # the manifest attests this training data was ALREADY
            # binned into a published cache: the restoring dataset
            # must have reused it (same key, manifest-valid open) —
            # a fresh re-bin here means the resume paid work the
            # cache existed to prevent (MED anomaly, obs/rules.py)
            cur = g.stream_identity() \
                if hasattr(g, "stream_identity") else None
            info = getattr(getattr(g, "train_set", None),
                           "stream", None) if cur is not None else None
            # a fresh ingest that ran BEFORE this checkpoint existed
            # (same-process save->restore) wasted nothing; only a
            # re-bin AFTER the manifest attested the cache counts
            hit = bool(cur and
                       cur.get("cache_key") == ck_stream.get("cache_key")
                       and info is not None and info.rebinned == 0
                       and (info.from_cache or info.mappers_reused or
                            float(meta.get("created", 0.0)) >=
                            getattr(info, "ingested_at", 0.0)))
            if not hit:
                Log.warning(
                    "checkpoint records streamed-ingest cache %s but "
                    "the resuming dataset %s — the resume re-binned "
                    "data the cache should have served",
                    str(ck_stream.get("cache_key", "?"))[:16],
                    "re-ingested from scratch" if cur is None or
                    info is None or not (info.from_cache or
                                         info.mappers_reused)
                    else "re-binned chunks" if info.rebinned
                    else "is keyed to different data/config")
            _telemetry.counters.incr("ingest_resumes")
            rec = self.recorder or _telemetry.get_recorder() or \
                getattr(g, "_telemetry", None)
            if rec is not None:
                rec.emit("ingest", event="resume", cache_hit=hit,
                         expected_key=str(
                             ck_stream.get("cache_key", ""))[:16],
                         actual_key=str((cur or {}).get(
                             "cache_key", ""))[:16],
                         rebinned=int(getattr(info, "rebinned", 0)
                                      if info is not None else 0))
        ck_pager = meta.get("pager")
        if ck_pager:
            # paged runs are byte-identical to resident, so any
            # geometry (or none at all) is a valid resume — log the
            # transition for triage only
            cur_pg = g.pager_identity() \
                if hasattr(g, "pager_identity") else None
            if cur_pg != ck_pager:
                Log.info(
                    "checkpoint was written by an out-of-core run "
                    "(page_rows=%s, n_pages=%s); resuming %s — "
                    "results are byte-identical either way",
                    ck_pager.get("page_rows", "?"),
                    ck_pager.get("n_pages", "?"),
                    "resident" if cur_pg is None else
                    "with page_rows=%s, n_pages=%s" % (
                        cur_pg.get("page_rows", "?"),
                        cur_pg.get("n_pages", "?")))
        raw = None
        if booster.train_set is not None:
            raw = booster.train_set.raw_mat
        g.restore_training_snapshot(loaded["snapshot"], raw=raw)
        booster.best_iteration = int(meta.get("best_iteration", -1))
        best = meta.get("best_score") or {}
        booster.best_score = {d: dict(m) for d, m in best.items()}
        Log.info("checkpoint: resumed at iteration %d from %s",
                 int(meta["iter"]), loaded["path"])
        return int(meta["iter"])
