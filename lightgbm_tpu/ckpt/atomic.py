"""Atomic durable file writes + the checkpoint fault-injection hook.

The torn-write discipline every checkpoint (and model) file in this
package follows: write to a temporary sibling in the SAME directory,
``fsync`` the file, ``os.replace`` onto the final name, then ``fsync``
the parent directory so the rename itself survives a crash.  A reader
therefore only ever sees either the complete old bytes or the complete
new bytes — never a prefix.  (The reference's ``SaveModelToFile`` has
no such contract: a crash mid-save leaves a truncated model file.)

Fault injection (tests / CI only) is env-gated so the recovery path is
provable, not just plausible:

- ``LTPU_CKPT_FAULT=crash_blob``      — die mid-blob-write (partial
  temp file, no manifest): the checkpoint directory never finalizes.
- ``LTPU_CKPT_FAULT=crash_manifest``  — die after the blobs but before
  the manifest: same outcome, later in the stream.
- ``LTPU_CKPT_FAULT=truncate_blob``   — finalize normally, then tear
  bytes off a blob in the FINAL directory (simulating lost pages):
  the loader must detect the size/hash mismatch and fall back.
- ``LTPU_CKPT_FAULT_AT=<n>``          — trigger on the n-th save of
  the process (1-based, default 1); other saves run clean.

``InjectedFault`` deliberately subclasses ``BaseException``: the save
path's ``except Exception`` cleanup must NOT swallow it (a real
SIGKILL wouldn't run cleanup either).
"""
from __future__ import annotations

import hashlib
import os
import tempfile

__all__ = ["atomic_write_bytes", "atomic_write_text", "fsync_dir",
           "sha256_file", "InjectedFault", "fault_armed",
           "consume_fault", "reset_fault_counter"]


class InjectedFault(BaseException):
    """Simulated mid-write crash (env-gated, tests only)."""


_fault_saves_seen = 0


def reset_fault_counter() -> None:
    global _fault_saves_seen
    _fault_saves_seen = 0


def fault_armed() -> str:
    """The fault mode armed for the CURRENT save, or ''.  Call once
    per save attempt — the call advances the save ordinal that
    ``LTPU_CKPT_FAULT_AT`` matches against."""
    global _fault_saves_seen
    mode = os.environ.get("LTPU_CKPT_FAULT", "")
    if not mode:
        return ""
    _fault_saves_seen += 1
    at = int(os.environ.get("LTPU_CKPT_FAULT_AT", "1") or 1)
    return mode if _fault_saves_seen == at else ""


def consume_fault(mode: str, point: str, path: str) -> None:
    """Fire the armed fault when the writer reaches ``point``."""
    if mode == "crash_blob" and point == "blob":
        with open(path, "wb") as f:
            f.write(b"\x00" * 7)   # the torn partial write
        raise InjectedFault(f"injected crash mid-blob at {path}")
    if mode == "crash_manifest" and point == "manifest":
        raise InjectedFault(f"injected crash before manifest at {path}")
    if mode == "truncate_blob" and point == "post_finalize":
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))


def fsync_dir(path: str) -> None:
    """Durably record directory-entry changes (renames, creates)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return   # e.g. platforms without directory fds
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> int:
    """temp + fsync + rename + parent fsync; returns bytes written."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".tmp_" + os.path.basename(path),
                               dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        # mkstemp creates 0600; a model file must keep the perms a
        # plain open() would have produced (existing mode, else
        # umask-derived) or cross-user readers lose access on reload
        try:
            mode = os.stat(path).st_mode & 0o777
        except OSError:
            umask = os.umask(0)
            os.umask(umask)
            mode = 0o666 & ~umask
        os.chmod(tmp, mode)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(directory)
    return len(data)


def atomic_write_text(path: str, text: str) -> int:
    return atomic_write_bytes(path, text.encode("utf-8"))


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()
