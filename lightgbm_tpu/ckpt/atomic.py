"""Atomic durable file writes + the checkpoint fault-injection hook.

The torn-write discipline every checkpoint (and model) file in this
package follows: write to a temporary sibling in the SAME directory,
``fsync`` the file, ``os.replace`` onto the final name, then ``fsync``
the parent directory so the rename itself survives a crash.  A reader
therefore only ever sees either the complete old bytes or the complete
new bytes — never a prefix.  (The reference's ``SaveModelToFile`` has
no such contract: a crash mid-save leaves a truncated model file.)

Fault injection routes through the unified registry
(``utils/faults.py``, point ``ckpt.save``) so checkpoint crashes
compose with serve/watcher/fleet faults in one chaos spec.  The PR 5
env pair keeps working (the registry folds it in):

- ``LTPU_CKPT_FAULT=crash_blob``      — die mid-blob-write (partial
  temp file, no manifest): the checkpoint directory never finalizes.
- ``LTPU_CKPT_FAULT=crash_manifest``  — die after the blobs but before
  the manifest: same outcome, later in the stream.
- ``LTPU_CKPT_FAULT=truncate_blob``   — finalize normally, then tear
  bytes off a blob in the FINAL directory (simulating lost pages):
  the loader must detect the size/hash mismatch and fall back.
- ``LTPU_CKPT_FAULT_AT=<n>``          — trigger on the n-th save of
  the process (1-based, default 1); other saves run clean.

The new-style equivalent is ``LTPU_FAULTS=ckpt.save:crash_blob@n``;
the hit counter advances once per SAVE (``fault_armed`` fires the
point), preserving the save-ordinal semantics.

``InjectedFault`` deliberately subclasses ``BaseException``: the save
path's ``except Exception`` cleanup must NOT swallow it (a real
SIGKILL wouldn't run cleanup either).
"""
from __future__ import annotations

import hashlib
import os
import tempfile

from ..utils import faults as _faults
from ..utils.faults import InjectedFault

__all__ = ["atomic_write_bytes", "atomic_write_text", "fsync_dir",
           "sha256_file", "InjectedFault", "fault_armed",
           "consume_fault", "reset_fault_counter"]


def reset_fault_counter() -> None:
    _faults.reset("ckpt.save")


def fault_armed() -> str:
    """The fault mode armed for the CURRENT save, or ''.  Call once
    per save attempt — the call advances the save ordinal that
    ``LTPU_CKPT_FAULT_AT`` (or a ``ckpt.save:...@n`` spec) matches
    against."""
    return _faults.fire("ckpt.save")


def consume_fault(mode: str, point: str, path: str) -> None:
    """Fire the armed fault when the writer reaches ``point``."""
    if mode == "crash_blob" and point == "blob":
        with open(path, "wb") as f:
            f.write(b"\x00" * 7)   # the torn partial write
        raise InjectedFault(f"injected crash mid-blob at {path}")
    if mode == "crash_manifest" and point == "manifest":
        raise InjectedFault(f"injected crash before manifest at {path}")
    if mode == "truncate_blob" and point == "post_finalize":
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))


def fsync_dir(path: str) -> None:
    """Durably record directory-entry changes (renames, creates)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return   # e.g. platforms without directory fds
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> int:
    """temp + fsync + rename + parent fsync; returns bytes written."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".tmp_" + os.path.basename(path),
                               dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        # mkstemp creates 0600; a model file must keep the perms a
        # plain open() would have produced (existing mode, else
        # umask-derived) or cross-user readers lose access on reload
        try:
            mode = os.stat(path).st_mode & 0o777
        except OSError:
            umask = os.umask(0)
            os.umask(umask)
            mode = 0o666 & ~umask
        os.chmod(tmp, mode)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(directory)
    return len(data)


def atomic_write_text(path: str, text: str) -> int:
    return atomic_write_bytes(path, text.encode("utf-8"))


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()
