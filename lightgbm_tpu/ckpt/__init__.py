"""Fault-tolerant checkpoint/resume subsystem (``docs/Checkpointing.md``).

Preemption-safe training: :class:`CheckpointManager` writes atomic,
schema-versioned, content-hashed snapshots of the COMPLETE training
state (tree tables, score carries, host PRNG streams, sampling-cycle
position, early-stopping state), and ``engine.train`` resumes from
them to a bit-identical continuation of the uninterrupted run —
pinned by ``tests/test_checkpoint.py`` across objectives x sampling
modes x fused/unfused super-step paths.
"""
from .atomic import atomic_write_bytes, atomic_write_text
from .manager import CheckpointError, CheckpointManager, SCHEMA_VERSION

__all__ = ["CheckpointManager", "CheckpointError", "SCHEMA_VERSION",
           "atomic_write_bytes", "atomic_write_text"]
