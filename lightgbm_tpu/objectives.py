"""Objective functions: gradients/hessians on device.

Capability parity with ``src/objective/`` (factory at
``objective_function.cpp:10-47``).  Each objective implements
``get_gradients(score) -> (grad, hess)`` over ``(num_data,)`` (or
``(num_class, num_data)`` for multiclass) device arrays, plus
``boost_from_score`` (initial score), ``convert_output`` (raw score →
prediction), optional per-leaf output renewal
(``RenewTreeOutput``, ``objective_function.h:38-47``) and constant-hessian
detection.

TPU-first: all math is vectorized jnp (fused by XLA into a single
elementwise pass over the score array); per-query ranking loops become
segment-id masked ops.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from .utils.log import Log

_REGISTRY: Dict[str, Type["Objective"]] = {}


def register(*names):
    def deco(cls):
        for n in names:
            _REGISTRY[n] = cls
        cls.name = names[0]
        return cls
    return deco


def create_objective(name: str, config) -> "Objective":
    """Factory (``ObjectiveFunction::CreateObjectiveFunction``)."""
    if name not in _REGISTRY:
        Log.fatal("unknown objective %s", name)
    return _REGISTRY[name](config)


class Objective:
    name = "base"
    is_constant_hessian = False
    num_model_per_iteration = 1
    # transform applied to raw score at predict time
    def __init__(self, config):
        self.config = config
        self.label: Optional[jax.Array] = None
        self.weight: Optional[jax.Array] = None

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weight = (jnp.asarray(metadata.weight, jnp.float32)
                       if metadata.weight is not None else None)

    def _w(self, grad, hess):
        if self.weight is not None:
            return grad * self.weight, hess * self.weight
        return grad, hess

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return raw

    def renew_tree_output(self, tree, score, leaf_idx, mask) -> None:
        """Optional per-leaf refit (L1/quantile/MAPE families)."""
        return None

    def _weighted_mean_label(self) -> float:
        lab = np.asarray(self.label, np.float64)
        if self.weight is not None:
            w = np.asarray(self.weight, np.float64)
            return float(np.sum(lab * w) / np.sum(w))
        return float(np.mean(lab))


@register("regression", "regression_l2", "l2", "mean_squared_error", "mse",
          "l2_root", "root_mean_squared_error", "rmse")
class RegressionL2(Objective):
    """L2 loss (``regression_objective.hpp`` RegressionL2loss).

    ``reg_sqrt`` fits sqrt(|label|) like the reference.
    """
    is_constant_hessian = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.config.reg_sqrt:
            lab = jnp.sign(self.label) * jnp.sqrt(jnp.abs(self.label))
            self.label = lab
        if self.weight is not None:
            self.is_constant_hessian = False

    def get_gradients(self, score):
        return self._w(score - self.label, jnp.ones_like(score))

    def boost_from_score(self, class_id=0):
        return self._weighted_mean_label()

    def convert_output(self, raw):
        if self.config.reg_sqrt:
            return np.sign(raw) * raw * raw
        return raw


@register("binary")
class Binary(Objective):
    """Log loss (``binary_objective.hpp``): labels {0,1} mapped to ±1,
    sigmoid scaling, ``scale_pos_weight`` / ``is_unbalance`` class
    weights, initial score log(p/(1-p))/sigmoid."""

    def __init__(self, config):
        super().__init__(config)
        # config-derived fields must exist for predictor-only use
        # (model loaded from file; init() never runs)
        self.sigmoid = float(config.sigmoid)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label)
        vals = np.unique(lab)
        if not np.all(np.isin(vals, [0.0, 1.0])):
            Log.fatal("binary objective requires 0/1 labels, got %s",
                      vals[:5])
        self.sigmoid = float(self.config.sigmoid)
        cnt_pos = float(np.sum(lab == 1))
        cnt_neg = float(np.sum(lab == 0))
        # minority class upweighting + multiplicative scale_pos_weight
        # (binary_objective.hpp:82-91)
        w_neg, w_pos = 1.0, 1.0
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= float(self.config.scale_pos_weight)
        self.label_weights = (w_neg, w_pos)
        self._p_mean = (cnt_pos * self.label_weights[1]) / max(
            cnt_pos * self.label_weights[1] +
            cnt_neg * self.label_weights[0], 1e-12)
        self.sign_label = jnp.asarray(np.where(lab == 1, 1.0, -1.0),
                                      jnp.float32)
        self.cls_weight = jnp.asarray(
            np.where(lab == 1, self.label_weights[1], self.label_weights[0]),
            jnp.float32)

    def get_gradients(self, score):
        # response = -yl*sigma / (1 + exp(yl*sigma*score))
        t = self.sign_label * self.sigmoid
        response = -t / (1.0 + jnp.exp(t * score))
        absr = jnp.abs(response)
        grad = response * self.cls_weight
        hess = absr * (self.sigmoid - absr) * self.cls_weight
        return self._w(grad, hess)

    def boost_from_score(self, class_id=0):
        p = min(max(self._p_mean, 1e-12), 1 - 1e-12)
        init = float(np.log(p / (1 - p)) / self.sigmoid)
        return init

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))
