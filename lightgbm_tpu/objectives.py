"""Objective functions: gradients/hessians on device.

Capability parity with ``src/objective/`` (factory at
``objective_function.cpp:10-47``).  Each objective implements
``get_gradients(score) -> (grad, hess)`` over ``(num_data,)`` (or
``(num_class, num_data)`` for multiclass) device arrays, plus
``boost_from_score`` (initial score), ``convert_output`` (raw score →
prediction), optional per-leaf output renewal
(``RenewTreeOutput``, ``objective_function.h:38-47``) and constant-hessian
detection.

TPU-first: all math is vectorized jnp (fused by XLA into a single
elementwise pass over the score array); per-query ranking loops become
segment-id masked ops.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from .utils.log import Log

_REGISTRY: Dict[str, Type["Objective"]] = {}


def register(*names):
    def deco(cls):
        for n in names:
            _REGISTRY[n] = cls
        cls.name = names[0]
        return cls
    return deco


def create_objective(name: str, config) -> "Objective":
    """Factory (``ObjectiveFunction::CreateObjectiveFunction``)."""
    if name not in _REGISTRY:
        Log.fatal("unknown objective %s", name)
    return _REGISTRY[name](config)


_EMPTY_F32 = None


def _empty_f32():
    """Cached 0-length weight sentinel (a fresh jnp.zeros per call is
    an extra eager dispatch on the hot path).  Created under
    ``ensure_compile_time_eval``: the first call may now happen inside
    a jit trace (``gradient_fn``), and caching a tracer in a global
    would leak it into every later trace."""
    global _EMPTY_F32
    if _EMPTY_F32 is None:
        with jax.ensure_compile_time_eval():
            _EMPTY_F32 = jnp.zeros((0,), jnp.float32)
    return _EMPTY_F32


class Objective:
    name = "base"
    is_constant_hessian = False
    num_model_per_iteration = 1
    # transform applied to raw score at predict time
    def __init__(self, config):
        self.config = config
        self.label: Optional[jax.Array] = None
        self.weight: Optional[jax.Array] = None

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weight = (jnp.asarray(metadata.weight, jnp.float32)
                       if metadata.weight is not None else None)

    def _w(self, grad, hess):
        if self.weight is not None:
            return grad * self.weight, hess * self.weight
        return grad, hess

    # Battery training (models/battery.py): objectives whose weight
    # handling is a pure gradient-time multiply can accept a per-trace
    # weight override (per-model CV fold masks riding as a traced
    # vector).  MAPE opts out — it bakes weights into its label
    # weighting at init, so an override would be silently ignored.
    supports_weight_override = True

    @contextlib.contextmanager
    def weight_override(self, weight):
        """Swap ``self.weight`` for the duration of a trace.  The
        override multiplies gradients/hessians at exactly the point
        solo weighted training multiplies metadata weights, so a fold
        mask entering here reproduces the solo weighted op order
        bit-for-bit."""
        saved = self.weight
        self.weight = weight
        try:
            yield
        finally:
            self.weight = saved

    def _jitted_gradients(self, impl, args, **statics):
        """Dispatch ``impl(*args, weight, *, weighted=..., **statics)``
        as ONE jitted program.  Eagerly, a gradient chain dispatches
        each (N,)-scale op as its own HBM round-trip; fused it runs as
        one pass.  ``weight`` rides as an argument (a closure over a
        big device array would embed it in the remote-compile payload);
        unweighted calls share a cached 0-length sentinel."""
        if getattr(self, "_grad_fn", None) is None:
            self._grad_fn = jax.jit(
                impl,
                static_argnames=tuple(statics) + ("weighted",))
        w = self.weight if self.weight is not None else _empty_f32()
        return self._grad_fn(*args, w, weighted=self.weight is not None,
                             **statics)

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def gradient_fn(self):
        """A pure JITTED ``score -> (grad, hess)`` device function,
        capturable inside a larger jitted program (the fused training
        super-step traces it inside a ``lax.scan`` body,
        ``models/gbdt.py``).

        The contract: the returned callable reads only ``score`` and
        device arrays fixed at ``init`` time (labels, weights, query
        layouts) — no host work, no Python state mutation beyond
        first-call jit caching.  Every built-in objective's
        ``get_gradients`` satisfies this (the label/weight tensors are
        device residents and the math is jnp), so the base
        implementation jits it; an objective whose gradients need
        per-iteration host work must override this to return ``None``,
        which excludes it from super-step fusion.

        The jit wrapper is ALSO what the sequential training loop
        calls: XLA's fused elementwise loops are not bit-identical to
        the same chain dispatched eagerly (measured on the CPU
        backend: a fused ``sqrt(x*x+c)`` differs in the last ulp), so
        routing both paths through one compiled function is what makes
        the fused super-step bit-exact against the per-iteration path
        — and it is the faster form anyway (one pass over the score
        array instead of one HBM round-trip per op)."""
        if getattr(self, "_gradient_fn_jit", None) is None:
            self._gradient_fn_jit = jax.jit(self.get_gradients)
        return self._gradient_fn_jit

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return raw

    def renew_tree_output(self, tree, score, leaf_idx, mask) -> None:
        """Optional per-leaf refit (L1/quantile/MAPE families)."""
        return None

    def _weighted_mean_label(self) -> float:
        lab = np.asarray(self.label, np.float64)
        if self.weight is not None:
            w = np.asarray(self.weight, np.float64)
            return float(np.sum(lab * w) / np.sum(w))
        return float(np.mean(lab))


@register("regression", "regression_l2", "l2", "mean_squared_error", "mse",
          "l2_root", "root_mean_squared_error", "rmse")
class RegressionL2(Objective):
    """L2 loss (``regression_objective.hpp`` RegressionL2loss).

    ``reg_sqrt`` fits sqrt(|label|) like the reference.
    """
    is_constant_hessian = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.config.reg_sqrt:
            lab = jnp.sign(self.label) * jnp.sqrt(jnp.abs(self.label))
            self.label = lab
        if self.weight is not None:
            self.is_constant_hessian = False

    def get_gradients(self, score):
        return self._w(score - self.label, jnp.ones_like(score))

    def boost_from_score(self, class_id=0):
        return self._weighted_mean_label()

    def convert_output(self, raw):
        if self.config.reg_sqrt:
            return np.sign(raw) * raw * raw
        return raw


def _weighted_percentile(values: np.ndarray, weights: Optional[np.ndarray],
                         alpha: float) -> float:
    """PercentileFun / WeightedPercentileFun (regression_objective.hpp)."""
    if len(values) == 0:
        return 0.0
    order = np.argsort(values)
    v = values[order]
    if weights is None:
        pos = alpha * (len(v) - 1)
        lo = int(np.floor(pos))
        hi = min(lo + 1, len(v) - 1)
        return float(v[lo] + (pos - lo) * (v[hi] - v[lo]))
    w = weights[order]
    cum = np.cumsum(w)
    threshold = alpha * cum[-1]
    idx = int(np.searchsorted(cum, threshold, side="left"))
    return float(v[min(idx, len(v) - 1)])


class _RenewableRegression(Objective):
    """Base for objectives whose leaf outputs are refit as per-leaf
    percentiles of the residuals (``RenewTreeOutput``,
    ``regression_objective.hpp``)."""
    renew_alpha = 0.5

    def renew_tree_output(self, tree, score, leaf_idx, mask) -> None:
        score = np.asarray(score)[0] if np.ndim(score) > 1 else \
            np.asarray(score)
        leaf_idx = np.asarray(leaf_idx)
        mask = np.asarray(mask)[:len(leaf_idx)]
        label = np.asarray(self.label, np.float64)
        weight = None if self.weight is None else np.asarray(self.weight)
        residual = label - score[:len(label)]
        in_bag = mask > 0
        for leaf in range(tree.num_leaves):
            rows = in_bag & (leaf_idx[:len(label)] == leaf)
            if not np.any(rows):
                continue
            tree.leaf_value[leaf] = self._renew_value(
                residual[rows], None if weight is None else weight[rows])

    def _renew_value(self, residuals, weights):
        return _weighted_percentile(residuals, weights, self.renew_alpha)


@register("regression_l1", "l1", "mean_absolute_error", "mae")
class RegressionL1(_RenewableRegression):
    """L1 loss: constant gradients with per-leaf median refit."""
    is_constant_hessian = True

    def get_gradients(self, score):
        return self._w(jnp.sign(score - self.label), jnp.ones_like(score))

    def boost_from_score(self, class_id=0):
        return _weighted_percentile(
            np.asarray(self.label, np.float64),
            None if self.weight is None else np.asarray(self.weight), 0.5)


@register("quantile")
class Quantile(_RenewableRegression):
    """Pinball loss at ``alpha`` with per-leaf quantile refit."""
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)
        self.renew_alpha = self.alpha

    def get_gradients(self, score):
        grad = jnp.where(self.label > score, -self.alpha, 1.0 - self.alpha)
        return self._w(grad, jnp.ones_like(score))

    def boost_from_score(self, class_id=0):
        return _weighted_percentile(
            np.asarray(self.label, np.float64),
            None if self.weight is None else np.asarray(self.weight),
            self.alpha)


@register("huber")
class Huber(Objective):
    """Huber loss with transition at ``alpha``."""
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)

    def get_gradients(self, score):
        d = score - self.label
        grad = jnp.clip(d, -self.alpha, self.alpha)
        return self._w(grad, jnp.ones_like(score))

    def boost_from_score(self, class_id=0):
        lab = np.asarray(self.label, np.float64)
        if self.weight is not None:
            w = np.asarray(self.weight, np.float64)
            return float(np.sum(lab * w) / np.sum(w))
        return float(np.mean(lab))


@register("fair")
class Fair(Objective):
    """Fair loss: c*d/(|d|+c) gradient (regression_objective.hpp)."""

    def __init__(self, config):
        super().__init__(config)
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        d = score - self.label
        denom = jnp.abs(d) + self.c
        grad = self.c * d / denom
        hess = self.c * self.c / (denom * denom)
        return self._w(grad, hess)


@register("poisson")
class Poisson(Objective):
    """Poisson regression with log link."""

    def __init__(self, config):
        super().__init__(config)
        self.max_delta = float(config.poisson_max_delta_step)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(np.asarray(metadata.label) < 0):
            Log.fatal("poisson objective requires non-negative labels")

    def get_gradients(self, score):
        grad = jnp.exp(score) - self.label
        hess = jnp.exp(score + self.max_delta)
        return self._w(grad, hess)

    def boost_from_score(self, class_id=0):
        return float(np.log(max(self._weighted_mean_label(), 1e-12)))

    def convert_output(self, raw):
        return np.exp(raw)


@register("mape")
class MAPE(_RenewableRegression):
    """Mean absolute percentage error: L1 with 1/|label| row weights and
    weighted-median leaf refit."""
    is_constant_hessian = True
    supports_weight_override = False  # weights baked into _label_weight

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label, np.float64)
        w = 1.0 / np.maximum(1.0, np.abs(lab))
        if metadata.weight is not None:
            w = w * np.asarray(metadata.weight, np.float64)
        w = w / np.sum(w) * num_data
        self._label_weight = jnp.asarray(w, jnp.float32)
        self.weight = None  # folded into _label_weight

    def get_gradients(self, score):
        grad = jnp.sign(score - self.label) * self._label_weight
        return grad, self._label_weight

    def _renew_value(self, residuals, weights):
        return _weighted_percentile(residuals, weights, 0.5)

    def renew_tree_output(self, tree, score, leaf_idx, mask):
        self.weight = self._label_weight  # residual weighting for refit
        super().renew_tree_output(tree, score, leaf_idx, mask)
        self.weight = None

    def boost_from_score(self, class_id=0):
        return _weighted_percentile(np.asarray(self.label, np.float64),
                                    np.asarray(self._label_weight), 0.5)


@register("gamma")
class Gamma(Objective):
    """Gamma regression with log link."""

    def get_gradients(self, score):
        e = jnp.exp(-score)
        grad = 1.0 - self.label * e
        hess = self.label * e
        return self._w(grad, hess)

    def boost_from_score(self, class_id=0):
        return float(np.log(max(self._weighted_mean_label(), 1e-12)))

    def convert_output(self, raw):
        return np.exp(raw)


@register("tweedie")
class Tweedie(Objective):
    """Tweedie deviance with variance power rho in [1, 2)."""

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score):
        a = jnp.exp((1.0 - self.rho) * score)
        b = jnp.exp((2.0 - self.rho) * score)
        grad = -self.label * a + b
        hess = (-self.label * (1.0 - self.rho) * a +
                (2.0 - self.rho) * b)
        return self._w(grad, hess)

    def boost_from_score(self, class_id=0):
        return float(np.log(max(self._weighted_mean_label(), 1e-12)))

    def convert_output(self, raw):
        return np.exp(raw)


@register("binary")
class Binary(Objective):
    """Log loss (``binary_objective.hpp``): labels {0,1} mapped to ±1,
    sigmoid scaling, ``scale_pos_weight`` / ``is_unbalance`` class
    weights, initial score log(p/(1-p))/sigmoid."""

    def __init__(self, config):
        super().__init__(config)
        # config-derived fields must exist for predictor-only use
        # (model loaded from file; init() never runs)
        self.sigmoid = float(config.sigmoid)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label)
        vals = np.unique(lab)
        if not np.all(np.isin(vals, [0.0, 1.0])):
            Log.fatal("binary objective requires 0/1 labels, got %s",
                      vals[:5])
        self.sigmoid = float(self.config.sigmoid)
        cnt_pos = float(np.sum(lab == 1))
        cnt_neg = float(np.sum(lab == 0))
        # minority class upweighting + multiplicative scale_pos_weight
        # (binary_objective.hpp:82-91)
        w_neg, w_pos = 1.0, 1.0
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= float(self.config.scale_pos_weight)
        self.label_weights = (w_neg, w_pos)
        # initial probability from per-row weights x class weights
        # (BinaryLogloss::BoostFromScore accumulates weighted sums)
        if metadata.weight is not None:
            sw = np.asarray(metadata.weight, np.float64)
            sum_pos = float(np.sum(sw * (lab == 1)))
            sum_neg = float(np.sum(sw * (lab == 0)))
        else:
            sum_pos, sum_neg = cnt_pos, cnt_neg
        self._p_mean = (sum_pos * self.label_weights[1]) / max(
            sum_pos * self.label_weights[1] +
            sum_neg * self.label_weights[0], 1e-12)
        self.sign_label = jnp.asarray(np.where(lab == 1, 1.0, -1.0),
                                      jnp.float32)
        self.cls_weight = jnp.asarray(
            np.where(lab == 1, self.label_weights[1], self.label_weights[0]),
            jnp.float32)

    def get_gradients(self, score):
        return self._jitted_gradients(
            self._grads_impl, (score, self.sign_label, self.cls_weight),
            sigmoid=self.sigmoid)

    @staticmethod
    def _grads_impl(score, sign_label, cls_weight, weight, *, sigmoid,
                    weighted):
        # response = -yl*sigma / (1 + exp(yl*sigma*score))
        t = sign_label * sigmoid
        response = -t / (1.0 + jnp.exp(t * score))
        absr = jnp.abs(response)
        grad = response * cls_weight
        hess = absr * (sigmoid - absr) * cls_weight
        if weighted:
            grad = grad * weight
            hess = hess * weight
        return grad, hess

    def boost_from_score(self, class_id=0):
        p = min(max(self._p_mean, 1e-12), 1 - 1e-12)
        init = float(np.log(p / (1 - p)) / self.sigmoid)
        return init

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))


@register("multiclass", "softmax")
class MulticlassSoftmax(Objective):
    """Softmax multiclass (``multiclass_objective.hpp``): one tree per
    class per iteration; grad = p - 1{y=k}, hess = 2 p (1-p)."""

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        if self.num_class < 2:
            Log.fatal("multiclass objective requires num_class >= 2")
        self.num_model_per_iteration = self.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label).astype(np.int32)
        if lab.min() < 0 or lab.max() >= self.num_class:
            Log.fatal("multiclass label out of range [0, %d)",
                      self.num_class)
        self._onehot = jnp.asarray(
            np.eye(self.num_class, dtype=np.float32)[lab].T)  # (K, N)
        counts = np.bincount(lab, minlength=self.num_class).astype(np.float64)
        self._class_init = np.log(np.maximum(counts / counts.sum(), 1e-10))

    def get_gradients(self, score):
        return self._jitted_gradients(self._grads_impl,
                                      (score, self._onehot))

    @staticmethod
    def _grads_impl(score, onehot, weight, *, weighted):
        # score (K, N)
        p = jax.nn.softmax(score, axis=0)
        grad = p - onehot
        hess = 2.0 * p * (1.0 - p)
        if weighted:
            grad = grad * weight[None, :]
            hess = hess * weight[None, :]
        return grad, hess

    def boost_from_score(self, class_id=0):
        return float(self._class_init[class_id])

    def convert_output(self, raw):
        # raw (rows, K)
        e = np.exp(raw - raw.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)


@register("multiclassova", "multiclass_ova", "ova", "ovr")
class MulticlassOVA(Objective):
    """One-vs-all multiclass: K independent binary objectives."""

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        if self.num_class < 2:
            Log.fatal("multiclassova requires num_class >= 2")
        self.num_model_per_iteration = self.num_class
        self.sigmoid = float(config.sigmoid)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label).astype(np.int32)
        self._sign = jnp.asarray(np.where(
            np.eye(self.num_class, dtype=bool)[lab].T, 1.0, -1.0
        ).astype(np.float32))  # (K, N)
        counts = np.bincount(lab, minlength=self.num_class).astype(np.float64)
        p = np.clip(counts / counts.sum(), 1e-12, 1 - 1e-12)
        self._class_init = np.log(p / (1 - p)) / self.sigmoid

    def get_gradients(self, score):
        t = self._sign * self.sigmoid
        response = -t / (1.0 + jnp.exp(t * score))
        absr = jnp.abs(response)
        grad = response
        hess = absr * (self.sigmoid - absr)
        if self.weight is not None:
            grad = grad * self.weight[None, :]
            hess = hess * self.weight[None, :]
        return grad, hess

    def boost_from_score(self, class_id=0):
        return float(self._class_init[class_id])

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))


@register("cross_entropy", "xentropy")
class CrossEntropy(Objective):
    """Cross-entropy for probabilistic labels in [0, 1]
    (``xentropy_objective.hpp:71``)."""

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label)
        if lab.min() < 0 or lab.max() > 1:
            Log.fatal("cross_entropy labels must be in [0, 1]")

    def get_gradients(self, score):
        z = jax.nn.sigmoid(score)
        return self._w(z - self.label, z * (1.0 - z))

    def boost_from_score(self, class_id=0):
        p = np.clip(self._weighted_mean_label(), 1e-12, 1 - 1e-12)
        return float(np.log(p / (1 - p)))

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-raw))


@register("cross_entropy_lambda", "xentlambda")
class CrossEntropyLambda(Objective):
    """Alternative-parameterization cross-entropy
    (``xentropy_objective.hpp:181``)."""

    def get_gradients(self, score):
        if self.weight is None:
            z = jax.nn.sigmoid(score)
            return z - self.label, z * (1.0 - z)
        w = self.weight
        y = self.label
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        grad = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d = c - 1.0
        b = (c / (d * d)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad, hess

    def boost_from_score(self, class_id=0):
        p = np.clip(self._weighted_mean_label(), 1e-12, 1 - 1e-12)
        return float(np.log(np.expm1(-np.log1p(-p))))  # log(exp(hhat)-1)

    def convert_output(self, raw):
        return np.log1p(np.exp(raw))


def default_label_gain(n: int = 31) -> np.ndarray:
    """label_gain = 2^i - 1 (``dcg_calculator.cpp:30``)."""
    return np.concatenate([[0.0], (2.0 ** np.arange(1, n).astype(np.float64)
                                   - 1.0)])


@register("lambdarank", "rank")
class LambdaRank(Objective):
    """LambdaRank with NDCG gains (``rank_objective.hpp:19``).

    TPU-first: the reference's per-query pairwise loops become padded
    (num_queries, max_docs) tensors — per-query sort, positional
    discounts and an all-pairs (q, i, j) lambda tensor, chunked over
    queries to bound memory.  Sigmoid uses the same
    2/(1+exp(2*sigma*d)) shape the reference tabulates
    (``rank_objective.hpp:194``).
    """

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.norm = bool(config.lambdamart_norm)
        self.max_position = int(config.max_position)
        gains = config.label_gain
        self.label_gain = (np.asarray(gains, np.float64) if gains
                           else default_label_gain())

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("lambdarank requires query information (set group)")
        qb = np.asarray(metadata.query_boundaries)
        self.num_queries = len(qb) - 1
        cnts = np.diff(qb)
        self.max_docs = int(cnts.max())
        lab = np.asarray(metadata.label).astype(np.int64)
        if lab.max() >= len(self.label_gain):
            Log.fatal("label %d exceeds label_gain table size %d",
                      int(lab.max()), len(self.label_gain))
        # padded (nq, mq) row-index matrix; N = padding sentinel
        nq, mq = self.num_queries, self.max_docs
        idx = np.full((nq, mq), num_data, dtype=np.int32)
        for q in range(nq):
            idx[q, :cnts[q]] = np.arange(qb[q], qb[q + 1])
        self._doc_idx = jnp.asarray(idx)
        self._doc_valid = jnp.asarray(idx < num_data)
        # inverse max DCG per query (truncated at max_position)
        gains_per_row = self.label_gain[lab]
        inv_max = np.zeros(nq)
        for q in range(nq):
            g = np.sort(gains_per_row[qb[q]:qb[q + 1]])[::-1]
            g = g[:self.max_position]
            dcg = np.sum(g / np.log2(np.arange(len(g)) + 2.0))
            inv_max[q] = 1.0 / dcg if dcg > 0 else 0.0
        self._inv_max_dcg = jnp.asarray(inv_max, jnp.float32)
        # label/gain in PADDED (nq, mq) layout, precomputed once:
        # gathering them per iteration costs two (nq*mq,)-element
        # gathers of constants (XLA gathers are the slowest op on this
        # target — see docs/Design.md)
        lab_pad = np.concatenate([lab, [-1]])
        gains_pad = np.concatenate([gains_per_row, [0.0]])
        self._lbl_mat = jnp.asarray(lab_pad[idx], jnp.int32)
        self._gain_mat = jnp.asarray(gains_pad[idx], jnp.float32)

    def get_gradients(self, score):
        # the whole pairwise computation runs as ONE jitted program:
        # eagerly, every (cq, mq, mq) intermediate of the lambda chain
        # materializes to HBM (tens of GB per iteration at this chip's
        # ~26 GB/s) — fused under jit it stays in registers/VMEM
        nq, mq = self._doc_idx.shape
        cq = max(1, min(nq, int(2e7 // max(mq * mq, 1))))
        nchunks = (nq + cq - 1) // cq
        n = int(score.reshape(-1).shape[0])
        return self._jitted_gradients(
            self._grads_impl,
            (score, self._doc_idx, self._doc_valid, self._inv_max_dcg,
             self._lbl_mat, self._gain_mat),
            n=n, nchunks=nchunks, cq=cq, norm=self.norm,
            sigmoid=self.sigmoid)

    @staticmethod
    def _grads_impl(score, doc_idx_all, valid_all, inv_max_all,
                    lbl_all, gain_all, weight, *, n, nchunks, cq, norm,
                    sigmoid, weighted):
        score = score.reshape(-1)
        sc_pad = jnp.concatenate([score, jnp.array([-jnp.inf],
                                                   score.dtype)])

        def query_chunk(args):
            doc_idx, valid, inv_max, lbl, gain = args
            s = sc_pad[doc_idx]                      # (cq, mq)
            order = jnp.argsort(-jnp.where(valid, s, -jnp.inf), axis=1,
                                stable=True)
            rank = jnp.argsort(order, axis=1)        # row -> position
            disc = 1.0 / jnp.log2(2.0 + rank.astype(jnp.float32))
            # pairwise (cq, mq, mq): i = high candidate, j = low
            li = lbl[:, :, None]
            lj = lbl[:, None, :]
            pair_ok = (li > lj) & valid[:, :, None] & valid[:, None, :]
            ds = s[:, :, None] - s[:, None, :]
            dg = gain[:, :, None] - gain[:, None, :]
            dd = jnp.abs(disc[:, :, None] - disc[:, None, :])
            delta = dg * dd * inv_max[:, None, None]
            if norm:
                smax = jnp.max(jnp.where(valid, s, -jnp.inf), axis=1)
                smin = jnp.min(jnp.where(valid, s, jnp.inf), axis=1)
                nz = (smax != smin)[:, None, None]
                delta = jnp.where(nz, delta / (0.01 + jnp.abs(ds)),
                                  delta)
            p = 2.0 / (1.0 + jnp.exp(jnp.clip(
                2.0 * sigmoid * ds, -60.0, 60.0)))
            lam = jnp.where(pair_ok, -delta * p, 0.0)
            hes = jnp.where(pair_ok, 2.0 * delta * p * (2.0 - p), 0.0)
            g_doc = jnp.sum(lam, axis=2) - jnp.sum(lam, axis=1)
            h_doc = jnp.sum(hes, axis=2) + jnp.sum(hes, axis=1)
            return doc_idx, g_doc, h_doc

        nq, mq = doc_idx_all.shape
        pad_q = nchunks * cq - nq
        di = jnp.concatenate([doc_idx_all,
                              jnp.full((pad_q, mq), n, jnp.int32)])
        dv = jnp.concatenate([valid_all,
                              jnp.zeros((pad_q, mq), bool)])
        im = jnp.concatenate([inv_max_all, jnp.zeros(pad_q,
                                                     jnp.float32)])
        lm = jnp.concatenate([lbl_all,
                              jnp.full((pad_q, mq), -1, jnp.int32)])
        gm = jnp.concatenate([gain_all,
                              jnp.zeros((pad_q, mq), jnp.float32)])
        grad = jnp.zeros(n + 1, jnp.float32)
        hess = jnp.zeros(n + 1, jnp.float32)
        idxs, gs, hs = jax.lax.map(
            query_chunk, (di.reshape(nchunks, cq, mq),
                          dv.reshape(nchunks, cq, mq),
                          im.reshape(nchunks, cq),
                          lm.reshape(nchunks, cq, mq),
                          gm.reshape(nchunks, cq, mq)))
        grad = grad.at[idxs.reshape(-1)].add(gs.reshape(-1))
        hess = hess.at[idxs.reshape(-1)].add(hs.reshape(-1))
        grad, hess = grad[:n], hess[:n]
        if weighted:
            grad = grad * weight
            hess = hess * weight
        return grad, hess
