from .tree import Tree, cat_bitset
from .gbdt import GBDT
from . import model_io
