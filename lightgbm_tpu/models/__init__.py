from .tree import Tree, cat_bitset
