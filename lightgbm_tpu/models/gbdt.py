"""GBDT boosting orchestrator.

Capability parity with ``src/boosting/gbdt.cpp``: Init wires
config/data/objective/metrics and the tree builder; ``TrainOneIter``
(``gbdt.cpp:335``) = gradients → bagging → per-class tree build → leaf
renewal → shrinkage → score update → first-iter bias absorption
(``new_tree->AddBias(init_score)``, ``gbdt.cpp:377``); plus rollback,
refit, and model text I/O hooks.

TPU-first: gradients/scores are device-resident, the tree build is one
jitted call (``ops/grow.py``) whose split records come back to host once
per tree to materialize a :class:`Tree`.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..io.dataset import Metadata, TpuDataset
from ..objectives import Objective, create_objective
from ..metrics import Metric
from ..utils.log import Log
from .tree import Tree, cat_bitset

_KEPS = 1e-15


def _threshold_l1(s, l1):
    if l1 <= 0:
        return np.asarray(s, np.float64)
    return np.sign(s) * np.maximum(np.abs(s) - l1, 0.0)


def records_to_tree(rec, config, train_set, counts_proxy=False) -> Tree:
    """Materialize ONE host :class:`Tree` from a fetched split-record
    dict.  Module-level (not a GBDT method) so the battery trainer
    (``models/battery.py``) can assemble per-member trees from stacked
    (B, K, ...) records with per-member configs without instantiating
    B GBDT drivers — the shared TpuDataset supplies the bin mappers."""
    cfg = config
    ds = train_set
    tree = Tree(cfg.num_leaves)

    def out(g, h):
        o = -np.sign(_thl1(g, cfg.lambda_l1)) * abs(
            _thl1(g, cfg.lambda_l1)) / (h + cfg.lambda_l2 + _KEPS)
        if cfg.max_delta_step > 0:
            o = np.clip(o, -cfg.max_delta_step, cfg.max_delta_step)
        return float(o)

    def _thl1(s, l1):
        return np.sign(s) * max(abs(s) - l1, 0.0) if l1 > 0 else s

    L1 = cfg.num_leaves - 1
    for i in range(L1):
        if not bool(rec["valid"][i]):
            break
        leaf = int(rec["leaf"][i])
        inner_f = int(rec["feature"][i])
        real_f = ds.real_feature_index(inner_f)
        mapper = ds.mappers[real_f]
        ls = rec["left_stats"][i]
        rs = rec["right_stats"][i]
        lv, rv = out(ls[0], ls[1]), out(rs[0], rs[1])
        if "rec_left_min" in rec:
            # monotone value constraints (the device loop clamped
            # identically; redo in f64 on the host-side outputs)
            lv = float(np.clip(lv, rec["rec_left_min"][i],
                               rec["rec_left_max"][i]))
            rv = float(np.clip(rv, rec["rec_right_min"][i],
                               rec["rec_right_max"][i]))
        gain = float(rec["gain"][i])
        if bool(rec["is_cat"][i]):
            bins = np.nonzero(rec["left_mask"][i])[0]
            cats = [mapper.bin_2_categorical[b] for b in bins
                    if 0 < b < len(mapper.bin_2_categorical)]
            if not cats:
                cats = [0]
            tree.split_categorical(
                leaf, real_f, cat_bitset(cats), lv, rv,
                float(ls[1]), float(rs[1]), int(round(ls[2])),
                int(round(rs[2])), gain, mapper.missing_type)
        else:
            thr_bin = int(rec["threshold"][i])
            tree.split(leaf, real_f, thr_bin,
                       mapper.bin_to_value(thr_bin), lv, rv,
                       float(ls[1]), float(rs[1])
                       , int(round(ls[2])), int(round(rs[2])), gain,
                       mapper.missing_type,
                       bool(rec["default_left"][i]))
        node = tree.num_leaves - 2
        pg, ph = ls[0] + rs[0], ls[1] + rs[1]
        tree.internal_value[node] = out(pg, ph)
    if "leaf_stats_exact" in rec:
        # quantized training: renew leaf outputs from the
        # full-precision per-leaf sums (RenewIntGradTreeOutput) so
        # leaf values carry no stochastic-rounding noise
        ex = np.asarray(rec["leaf_stats_exact"], np.float64)
        for leaf in range(tree.num_leaves):
            if leaf < len(ex) and ex[leaf, 2] > 0:
                tree.leaf_value[leaf] = out(ex[leaf, 0], ex[leaf, 1])
        if counts_proxy:
            # two-column passes record hess sums in the count slots;
            # restore REAL counts: leaves from the exact renewal
            # sums, internal nodes by one REVERSE-id sweep (a
            # child's node id always exceeds its parent's, so its
            # count is ready first; no recursion — chain-shaped
            # trees can exceed Python's recursion limit)
            for leaf in range(tree.num_leaves):
                if leaf < len(ex):
                    tree.leaf_count[leaf] = int(round(ex[leaf, 2]))

            def child_count(c):
                return tree.leaf_count[~c] if c < 0 else \
                    tree.internal_count[c]

            for node in range(tree.num_leaves - 2, -1, -1):
                tree.internal_count[node] = \
                    child_count(tree.left_child[node]) + \
                    child_count(tree.right_child[node])
    return tree


@dataclasses.dataclass
class ValidSet:
    name: str
    raw: np.ndarray          # raw feature matrix (rows, total_features)
    metadata: Metadata
    score: np.ndarray = None  # accumulated raw score
    xt: object = None        # device (F_pad, rows) binned matrix, or None
    # per-tree leaf assignment (uint8/16), kept only when the boosting
    # mode tracks train leaves (DART): drop/renormalize replays become
    # numpy leaf-table lookups instead of per-tree host traversals
    leaf_idx_per_tree: list = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if self.score is None:
            n = self.raw.shape[0]
            k = 1
            self.score = np.zeros((k, n), dtype=np.float64)


class GBDT:
    """Gradient Boosting Decision Tree driver (single class for now;
    multiclass lands with the multiclass objective)."""

    @property
    def models(self) -> List[Tree]:
        """The tree list.  Pipelined boosting defers the host
        materialization of the newest tree by one iteration (its
        records fetch hides behind the next tree's device build); ANY
        reader flushes first, so the list is always complete from the
        outside."""
        if getattr(self, "_pending", None) is not None:
            self._flush_pending()
        return self._models

    @models.setter
    def models(self, value: List[Tree]) -> None:
        if getattr(self, "_pending", None) is not None:
            self._flush_pending()
        self._models = list(value)
        self._invalidate_predictor()

    def _invalidate_predictor(self) -> None:
        """Drop the flattened-forest cache (ops/predict.py).  Appends
        and pops are covered by the tree-count in the cache key; this
        hook is for IN-PLACE mutations of existing trees — DART
        renormalization, refit, merge splices, model-list swaps.  The
        per-tree handoff rows (``_tree_flats``) are cleared too: an
        in-place mutation invalidates the extracted row, and the
        device-handoff path re-extracts lazily."""
        self._model_version = getattr(self, "_model_version", 0) + 1
        self._flat_cache = None
        self._shap_cache = None
        self._tree_flats = []

    def __init__(self, config: Config, train_set: TpuDataset,
                 objective: Optional[Objective],
                 metrics: Sequence[Metric] = (), mesh=None):
        import jax
        import jax.numpy as jnp
        from ..ops.grow import DistConfig, GrowParams, build_tree
        from ..ops.histogram import _pad_bins, multi_width
        from ..ops.split import SplitParams

        self.config = config
        self.train_set = train_set
        self.objective = objective
        self.metrics = list(metrics)
        self._models: List[Tree] = []
        self._model_version = 0
        self._flat_cache = None     # (key, FlatForest) — ops/predict.py
        self._shap_cache = None     # (key, ShapForest) — ops/shap.py
        self._tree_flats = []       # per-tree handoff rows (TreeFlat)
        self._pending = None        # in-flight tree (pipelined boosting)
        self._stop_flag = False
        self._pipeline_enabled = True  # DART/RF opt out
        # fused boosting super-steps (config.fused_iters > 1): one
        # jitted lax.scan runs K iterations on device; the block state
        # below serves its trees one per train_one_iter call
        self._superstep_enabled = True  # DART/RF opt out
        self._fused_block = None        # fetched block being served
        self._sq = []                   # dispatched-but-unfetched blocks
        self._superstep_jit = None      # lazily-built jitted scan
        self._fused_has_bagging = False
        self._trees_dispatched = 0  # quantization PRNG stream position
        self.iter = 0
        self.num_class = max(config.num_class, 1)
        self.num_tree_per_iteration = 1
        if objective is not None:
            self.num_tree_per_iteration = getattr(
                objective, "num_model_per_iteration", 1)
        self.shrinkage_rate = config.learning_rate
        self.num_data = train_set.num_data
        self.valid_sets: List[ValidSet] = []
        self._prev_score = None
        self._prev_valid_scores: List[np.ndarray] = []
        # RF averages tree outputs instead of summing (rf.hpp:22)
        self.average_output = False
        # DART needs per-tree train contributions to drop/restore them
        self._track_train_leaf = False
        self._train_leaf_idx: List[Optional[np.ndarray]] = []

        F = len(train_set.used_features)
        self.num_features = F
        mappers = [train_set.mappers[i] for i in train_set.used_features]
        self.max_bin = int(2 ** np.ceil(np.log2(max(
            train_set.max_bin_count, 2))))
        # per-feature static descriptor arrays
        self._num_bins = jnp.asarray([m.num_bin for m in mappers], jnp.int32)
        self._missing_type = jnp.asarray(
            [m.missing_type for m in mappers], jnp.int32)
        from ..io.binning import BIN_CATEGORICAL
        self._is_cat = jnp.asarray(
            [m.bin_type == BIN_CATEGORICAL for m in mappers], bool)

        use_pallas = (config.device_type != "cpu" and
                      jax.default_backend() not in ("cpu",))
        from ..utils.env import pallas_interpret_forced
        if not use_pallas and pallas_interpret_forced():
            # LTPU_PALLAS_INTERPRET: the interpret-mode CPU parity
            # lane — every Pallas kernel (histogram tiers, routed
            # passes, the best-split scan) runs interpreted so tier-1
            # exercises the kernel paths without a TPU.  Correctness
            # only; interpreter wall time is meaningless.
            use_pallas = True
        rpb = int(config.tpu_rows_per_block)
        n = train_set.num_data

        # resolve the tree learner FIRST: the feature-padded width (and
        # with it the static per-feature constraint tuples) depends on
        # the mesh sharding
        learner = config.tree_learner
        num_shards = 1
        mesh_shape2d = None
        if learner not in ("serial", ""):
            from ..parallel import resolve_num_shards
            from ..utils.env import maybe_init_distributed
            # multi-host entry (env-gated, no-op single-host): join the
            # distributed runtime BEFORE counting devices so the mesh
            # factors over the global device set
            maybe_init_distributed()
            num_shards = resolve_num_shards(config, mesh)
            if num_shards <= 1:
                Log.warning("tree_learner=%s requested but only one device "
                            "is available; using the serial learner",
                            learner)
                learner = "serial"
        dist_active = learner not in ("serial", "") and num_shards > 1
        if dist_active and learner == "data2d":
            from ..parallel.learners import (factor_mesh_shape,
                                             parse_mesh_shape)
            if mesh is not None:
                mesh_shape2d = tuple(int(s) for s in mesh.devices.shape)
            elif getattr(config, "mesh_shape", ""):
                mesh_shape2d = parse_mesh_shape(config.mesh_shape)
                # an explicit shape wins over the device count: the
                # builder raises when the host cannot satisfy it
                num_shards = mesh_shape2d[0] * mesh_shape2d[1]
            else:
                mesh_shape2d = factor_mesh_shape(num_shards)
        self._mesh_shape2d = mesh_shape2d

        from ..parallel.learners import pad_features_for, pad_rows_for
        row_block = rpb if use_pallas else 1
        kind = learner if dist_active else "serial"
        # per-AXIS shard counts: the 2-D learner pads rows to its row
        # axis and features to its feature axis; 1-D learners key both
        # off the flat width (the pad helpers ignore the irrelevant one)
        row_shards = mesh_shape2d[0] if mesh_shape2d else num_shards
        feat_shards = mesh_shape2d[1] if mesh_shape2d else num_shards
        self._n_pad = pad_rows_for(kind, row_shards, n, row_block)
        self._F_pad = pad_features_for(kind, feat_shards, F)

        monotone, penalty = self._constraint_tuples(config, train_set, F)
        forced = self._forced_splits(config, train_set, dist_active)

        # EFB bundling (FindGroups/FastFeatureBundling,
        # dataset.cpp:38-180): serial learner only; bundles capped at
        # the histogram bin budget so the device tensors keep shape
        self._bundles = None
        self._bundle_maps = None
        if config.enable_bundle and not dist_active and F > 1:
            from ..io.binning import BIN_CATEGORICAL as _CAT
            from ..io.bundle import find_bundles
            db = np.asarray(
                [0 if mappers[j].bin_type == _CAT
                 else mappers[j].default_bin for j in range(F)], np.int32)
            nb_arr = np.asarray([m.num_bin for m in mappers], np.int32)
            bundles = find_bundles(
                train_set.binned, nb_arr, db,
                max_conflict_rate=config.max_conflict_rate,
                bin_budget=min(config.max_bin, 255),
                seed=config.data_random_seed)
            # cost model for the one-hot-matmul histogram: work is
            # columns x KERNEL-padded bin width (the kernel pads bins
            # to a multiple of 8, so 2-bin one-hot indicator columns
            # still stream 8 one-hot rows each — comparing unpadded
            # widths wrongly rejected bundling exactly on the one-hot
            # datasets EFB exists for)
            B_bun = int(bundles.group_num_bins.max())
            # the committed device width is max(max_bin, B_bun): cost
            # the bundled pass at exactly that width
            cost_bundled = bundles.num_groups * _pad_bins(
                max(self.max_bin, B_bun))
            cost_plain = F * _pad_bins(self.max_bin)
            if bundles.num_groups < F and cost_bundled < 0.95 * cost_plain:
                self._bundles = bundles
                # commit the width that was costed: the kernel pads to
                # a multiple of 8 itself, so rounding max_bin up to a
                # power of two here would stream more one-hot rows than
                # the acceptance decision accounted for
                self.max_bin = max(self.max_bin, B_bun)
                B = self.max_bin
                fix = np.zeros((F, B), np.float32)
                for f in range(F):
                    if not bundles.is_singleton[bundles.group_id[f]]:
                        fix[f, db[f]] = 1.0
                self._bundle_maps = (
                    jnp.asarray(bundles.group_id),
                    jnp.asarray(bundles.to_bundle_map(B, nb_arr)),
                    jnp.asarray(bundles.from_bundle_map(B, nb_arr)),
                    jnp.asarray(fix))
                Log.info("EFB: bundled %d features into %d groups",
                         F, bundles.num_groups)

        # HistogramPool memory policy: the (L, G, B, 3) pool enables
        # the subtraction trick; when it exceeds histogram_pool_size
        # (or a 4 GB default), children are recomputed fresh instead
        G_cols = self._bundles.num_groups if self._bundles else self._F_pad
        pool_bytes = (config.num_leaves * G_cols * self.max_bin * 3 * 4)
        cap = config.histogram_pool_size * 1e6 \
            if config.histogram_pool_size > 0 else 4e9
        use_pool = pool_bytes <= cap
        if not use_pool and forced:
            Log.warning("forced splits require the histogram pool; "
                        "keeping the pool despite histogram_pool_size")
            use_pool = True
        if not use_pool:
            Log.info("histogram pool (%.0f MB) exceeds budget; "
                     "recomputing child histograms", pool_bytes / 1e6)

        any_cat = bool(any(m.bin_type == BIN_CATEGORICAL
                           for m in mappers))
        any_missing = bool(any(m.missing_type != 0 for m in mappers))
        # wave growth composes with ALL parallel learners the way the
        # reference's GPU learner composes by template parameter
        # (data_parallel_tree_learner.cpp:258-259, tree_learner.cpp:
        # 9-33): data psums whole-wave histograms, feature merges
        # children bests by a batched all-gather arg-max, voting
        # psums only the elected features' histograms (grow.py)
        # data2d runs the non-wave loop: its per-axis collective
        # schedule (row-axis hist psum, feature-axis merge) is defined
        # on the per-leaf passes, and the wave path's whole-tensor
        # psum would forfeit the O(1/F_axis) histogram-byte cut
        wave_on = bool(config.wave_splits and use_pool and not forced
                       and learner != "data2d")
        # two-column quantized passes (W=64): legal only when the count
        # channel is provably redundant (GrowParams.two_col contract).
        # With missing values the default-direction "any missing data
        # here?" test reads the hess-copy channel instead of a count —
        # a row whose quantized hess rounds to 0 is then treated as
        # absent for direction choice only (both directions tie in
        # gain in that case; quality is pinned by the NaN-injection
        # oracle test).  Categorical features still gate it off: their
        # scans read REAL counts (cnt_ok, min_data_per_group).
        two_col = bool(
            config.use_quantized_grad and wave_on and
            self._bundles is None and not any_cat and
            config.min_data_in_leaf <= 1 and
            config.min_sum_hessian_in_leaf > 0)
        self._counts_proxy = two_col
        # coarse-to-fine refinement (hist_refinement): wave passes
        # stream Bc + R one-hot rows instead of the full padded bin
        # count; exactness caveat documented at GrowParams.refine_shift.
        # Measured on v5e: every pass carries ~25 ms of fixed cost
        # (~11 ms bins-matrix HBM read + kernel fixed work), so paying
        # it twice per wave only wins where the STREAM term dominates
        # the floor.  Stream ∝ F x padded(B): at 28 x 256 (7168 units,
        # the 255-bin bench) c2f measured 2x faster; at 28 x 64 it
        # measured slower (52 vs 45 ms/wave); wide-and-shallow shapes
        # (e.g. 2000 features x 63 bins = 128k units) are stream-bound
        # again — hence the stream-size gate rather than a pure
        # bin-count one.
        refine_shift = 0
        if (config.hist_refinement and wave_on and
                (not dist_active or learner == "data") and
                self._bundles is None and not any_cat and
                self.max_bin >= 48 and
                F * _pad_bins(self.max_bin) >= 7000):
            # missing values ride a RESERVED last coarse slot (grow.py
            # Bc_c2f) and a default-left row in the routed lane tables
            refine_shift = 4 if self.max_bin > 64 else 3
        # best-split engine (split_kernel=auto|pallas|xla): the Pallas
        # kernel family scans histograms on-chip (fused epilogue in
        # the batched passes + the standalone per-(leaf, feature-tile)
        # kernel), eliminating the histogram→split HBM round-trip.
        # Numerical serial configs only; every rejection records the
        # gate (tier telemetry) so a TPU run silently landing on the
        # XLA scan is triageable (tools/triage_run.py MED anomaly).
        split_req = str(config.split_kernel).lower() or "auto"
        if split_req not in ("auto", "pallas", "xla"):
            # an unrecognized value must NOT silently land on the
            # interpreter lane (pallas-on-cpu is orders of magnitude
            # slower than the XLA scan it would replace)
            Log.warning("unknown split_kernel=%r; using auto",
                        config.split_kernel)
            split_req = "auto"
        split_kernel, split_gate = "xla", None
        if split_req == "xla":
            split_gate = "split_kernel=xla"
        elif any_cat:
            split_gate = ("categorical scans (one-vs-other / sorted "
                          "many-vs-many) read the XLA path")
        elif self._bundles is not None:
            split_gate = "EFB bundles active (histogram expansion)"
        elif dist_active:
            split_gate = f"tree_learner={learner}"
        elif forced:
            split_gate = "forced splits"
        elif refine_shift:
            split_gate = ("c2f refinement scans coarse+window "
                          "(hist_refinement)")
        elif split_req == "auto" and not use_pallas:
            split_gate = ("cpu backend (split_kernel=pallas or "
                          "LTPU_PALLAS_INTERPRET=1 runs the "
                          "interpret lane)")
        else:
            # split_req "pallas" on a CPU backend is honored via the
            # interpret lane (ops/split.py pallas_interpret)
            split_kernel = "pallas"
        self.grow_params = GrowParams(
            split=SplitParams(
                max_bin=self.max_bin,
                lambda_l1=config.lambda_l1,
                lambda_l2=config.lambda_l2,
                min_data_in_leaf=config.min_data_in_leaf,
                min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
                min_gain_to_split=config.min_gain_to_split,
                max_delta_step=config.max_delta_step,
                max_cat_to_onehot=config.max_cat_to_onehot,
                max_cat_threshold=config.max_cat_threshold,
                cat_l2=config.cat_l2,
                cat_smooth=config.cat_smooth,
                min_data_per_group=config.min_data_per_group,
                monotone=monotone,
                penalty=penalty,
                # static dataset facts: trace-time dead-branch removal
                # in the split scan (no cat -> no bin sorts, no missing
                # -> one threshold direction)
                any_cat=any_cat,
                any_missing=any_missing,
                counts_proxy=two_col),
            num_leaves=config.num_leaves,
            max_depth=config.max_depth,
            hist_impl="pallas" if use_pallas else "segsum",
            rows_per_block=rpb,
            dist=DistConfig(top_k=config.top_k),
            forced=forced,
            bundled=self._bundles is not None,
            use_hist_pool=use_pool,
            # quantized-gradient histograms: small ints are exact in
            # bf16, halving the value columns; serial learner, or any
            # parallel learner under wave growth (shard-consistent
            # scale via pmax; noise hashed from global row index)
            quantize=(config.num_grad_quant_bins
                      if (config.use_quantized_grad and
                          (not dist_active or wave_on or
                           learner == "data2d"))
                      else 0),
            spec_tolerance=float(config.speculative_tolerance),
            # wave growth (wave_splits): top-W splits applied per loop
            # step from one batched pass; rides the speculative kernel
            wave=wave_on,
            two_col=two_col,
            refine_shift=refine_shift,
            split_kernel=split_kernel,
            # speculative child arming fills the MXU lanes (21 leaves x
            # 6 value columns, 42 x 3 quantized, 64 x 2 two-column);
            # enabled on the accelerator path where the batched pallas
            # kernel exists, or anywhere when wave growth asks for it
            speculate=(min(multi_width(config.use_quantized_grad,
                                       two_col), config.num_leaves)
                       if ((use_pallas or config.wave_splits) and
                           (not dist_active or wave_on) and
                           use_pool and not forced)
                       else 0))

        # ---- device-block pager (io/pager.py, docs/Streaming.md
        # "Out-of-core on device"): decide whether the binned matrix
        # trains RESIDENT or PAGED.  Auto triggers when ONE device's
        # matrix block would exceed hbm_budget_mb; "on" forces paging
        # and fails loudly on a paged-ineligible config instead of
        # silently training resident over budget ----
        old_pager = getattr(self, "_pager", None)
        if old_pager is not None:       # remesh re-runs __init__
            old_pager.abort()
            old_pager.close()
        self._pager = None
        self._pager_view = None
        self._pager_last = None
        paged_req = str(getattr(config, "paged_training", "auto")
                        or "auto").lower()
        hbm_budget = float(getattr(config, "hbm_budget_mb", 0.0) or 0.0)
        pg_out_cols = self._bundles.num_groups \
            if self._bundles is not None else self._F_pad
        pg_kind = learner if dist_active else "serial"
        pg_row_shards = (mesh_shape2d[0] if pg_kind == "data2d" else
                         num_shards if pg_kind in ("data", "voting")
                         else 1)
        pg_feat_shards = (mesh_shape2d[1] if pg_kind == "data2d" else
                          num_shards if pg_kind == "feature" else 1)
        if self._bundles is not None:
            pg_dtype = self._bundles.bundle_matrix(
                np.asarray(train_set.binned[:1])).dtype
        else:
            pg_dtype = train_set.binned.dtype
        pg_f_loc = pg_out_cols // max(pg_feat_shards, 1)
        pg_n_loc = self._n_pad // max(pg_row_shards, 1)
        per_dev_bytes = pg_f_loc * pg_n_loc * np.dtype(pg_dtype).itemsize
        want_paged = paged_req == "on" or (
            paged_req == "auto" and hbm_budget > 0 and
            per_dev_bytes > hbm_budget * (1 << 20))
        if want_paged:
            gp = self.grow_params
            if gp.hist_impl != "segsum":
                pg_gate = ("hist_impl=pallas — the on-chip histogram "
                           "tiers read the resident matrix")
            elif gp.wave or gp.speculate > 1:
                pg_gate = ("wave/speculative growth batches "
                           "multi-leaf passes over the resident matrix")
            elif split_kernel == "pallas":
                pg_gate = "split_kernel=pallas reads resident tiles"
            else:
                pg_gate = None
            if pg_gate is not None:
                if paged_req == "on":
                    raise ValueError(
                        f"paged_training=on, but this config is "
                        f"paged-ineligible: {pg_gate}.  Paged "
                        f"training runs the baseline segsum+xla lane "
                        f"(docs/Streaming.md)")
                Log.warning("paged_training=auto: %s; training "
                            "resident", pg_gate)
                want_paged = False
        if want_paged:
            from ..io.pager import PageStore, plan_pages
            pg_plan = plan_pages(
                pg_n_loc, pg_f_loc, np.dtype(pg_dtype).itemsize,
                hbm_budget_mb=hbm_budget,
                page_rows=int(getattr(config, "paged_page_rows", 0)
                              or 0))
            self._pager = PageStore(
                train_set.binned, n_rows=n, n_pad=self._n_pad,
                out_cols=pg_out_cols, plan=pg_plan,
                row_shards=pg_row_shards, feat_shards=pg_feat_shards,
                transform=(self._bundles.bundle_matrix
                           if self._bundles is not None else None),
                dtype=pg_dtype,
                prefetch=bool(getattr(config, "stream_prefetch",
                                      True)))
            Log.info("paged training: %d pages x %d rows per device "
                     "block (%.1f MB resident vs %.1f MB paged "
                     "double-buffer)", pg_plan.n_pages,
                     pg_plan.page_rows, per_dev_bytes / 1e6,
                     2 * pg_plan.page_bytes *
                     np.dtype(pg_dtype).itemsize / 1e6)

        # parallel tree learner over the device mesh
        # (tree_learner={data,feature,voting}, tree_learner.cpp:9-33)
        self._dist = None
        if dist_active:
            from ..parallel import DistributedBuilder
            self._dist = DistributedBuilder(
                learner, self.grow_params, num_shards, mesh,
                mesh_shape=mesh_shape2d, pager=self._pager)
            if self._pager is not None:
                self._pager_view = self._dist.pager_view
            if learner == "data2d":
                Log.info("tree_learner=data2d over a %dx%d "
                         "(data x feature) device mesh",
                         self._dist.row_shards, self._dist.feat_shards)
            else:
                Log.info("tree_learner=%s over a %d-way device mesh",
                         learner, num_shards)
        self._stream_upload = None
        stream_info = getattr(train_set, "stream", None)
        if self._pager is not None:
            # paged lane: the binned matrix NEVER materializes on
            # device.  Dispatch signatures keep a replicated dummy
            # operand in the xt slot (shapes/specs stay uniform) and
            # the traced programs read pages through the PagedXt
            # view — the streamed cache mmap and the in-memory binned
            # array are served by the same PageStore, so no upload
            # window or host-side transpose happens at all
            self._xt = jnp.zeros((1, 8), dtype=pg_dtype)
            if self._pager_view is None:
                self._pager_view = self._pager.view("serial")
        elif stream_info is not None:
            # streamed dataset (io/stream.py): the binned matrix is a
            # read-only mmap over the crash-safe cache — upload it in
            # budgeted double-buffered windows instead of
            # materializing the full (F_pad, n_pad) transpose on the
            # host.  The resulting device array is value-identical to
            # the in-memory path's, so everything downstream (fused
            # scans, sharded placement, checkpoint replay) is shared.
            from ..io.stream import BlockFetcher
            out_cols = self._bundles.num_groups \
                if self._bundles is not None else self._F_pad
            fetcher = BlockFetcher(
                train_set.binned, n_rows=n, n_pad=self._n_pad,
                out_cols=out_cols,
                window_rows=stream_info.window_rows,
                transform=(self._bundles.bundle_matrix
                           if self._bundles is not None else None),
                prefetch=stream_info.prefetch,
                read_retries=int(getattr(config, "stream_read_retries",
                                         3)),
                backoff_base_s=float(getattr(config,
                                             "stream_backoff_base_s",
                                             0.1)))
            # windows land directly in the learner's layout (data2d:
            # the P("feature", "data") tiles) — no single-device
            # staging copy, no re-shard afterwards
            self._xt = fetcher.upload(
                sharding=(self._dist.shardings()["xt"]
                          if self._dist is not None else None))
            self._stream_upload = fetcher.stats()
        else:
            if self._bundles is not None:
                xt = self._bundles.bundle_matrix(
                    train_set.binned).T  # (G, N)
            else:
                xt = train_set.binned.T  # (F, N) narrow uint8/16
            col_pad = 0 if self._bundles is not None \
                else self._F_pad - F
            xt = np.pad(xt, ((0, col_pad), (0, self._n_pad - n)))
            # NARROW dtype end to end: host->device link (14 MB/s
            # tunnel) AND device residency (uint8 = 295 MB at bench
            # shape vs 1.18 GB int32); the pallas kernels and routing
            # selects widen per tile
            self._xt = jnp.asarray(xt)
        self._base_mask = jnp.asarray(
            np.pad(np.ones(n, np.float32), (0, self._n_pad - n)))
        if self._F_pad != F:
            # padded features are trivial: one bin, never splittable
            self._num_bins = jnp.concatenate(
                [self._num_bins, jnp.ones(self._F_pad - F, jnp.int32)])
            self._missing_type = jnp.concatenate(
                [self._missing_type, jnp.zeros(self._F_pad - F, jnp.int32)])
            self._is_cat = jnp.concatenate(
                [self._is_cat, jnp.zeros(self._F_pad - F, bool)])
        if self._dist is not None:
            # mesh-resident training state: place every persistent
            # tensor with the learner's NamedSharding ONCE, so neither
            # the per-tree dispatch nor the fused super-step re-shards
            # host-placed global arrays on every call (the per-shard
            # dispatch overhead behind the WEAKSCALE degradation)
            shd = self._dist.shardings()
            if self._pager is None and stream_info is None:
                # streamed uploads were already placed window-by-window
                self._xt = jax.device_put(self._xt, shd["xt"])
            self._base_mask = jax.device_put(self._base_mask, shd["row"])
            self._num_bins = jax.device_put(self._num_bins, shd["feat"])
            self._missing_type = jax.device_put(self._missing_type,
                                                shd["feat"])
            self._is_cat = jax.device_put(self._is_cat, shd["feat"])
        self._build_tree = build_tree if self._dist is None else self._dist
        if self._pager is not None and self._dist is None:
            # serial paged per-tree dispatch: the jitted builder closes
            # over the PagedXt view (a trace-time object, not a pytree
            # leaf) and ignores the dummy xt operand — same signature
            # as build_tree, so the dispatch sites stay untouched
            import functools as _ft
            from ..ops.grow import build_tree_impl as _bt_impl
            view = self._pager_view

            def _paged_build(xt, grad, hess, mask, fmask, nb, mt, cat,
                             params, bundle_maps=None, quant_key=None):
                return _bt_impl(view, grad, hess, mask, fmask, nb, mt,
                                cat, params, bundle_maps=bundle_maps,
                                quant_key=quant_key)

            self._build_tree = _ft.partial(
                jax.jit, static_argnames=("params",))(_paged_build)

        # scores: (num_tree_per_iteration, N) device
        k = self.num_tree_per_iteration
        score = np.zeros((k, n), dtype=np.float32)
        if train_set.metadata.init_score is not None:
            init = np.asarray(train_set.metadata.init_score,
                              np.float64).reshape(-1)
            score += init.reshape(k, n) if init.size == k * n else init
        self._score = jnp.asarray(score)
        if self._dist is not None:
            # the score carry lives on the mesh too (replicated): the
            # fused super-step donates it in place and the carry never
            # leaves the device mesh between blocks
            self._score = jax.device_put(self._score,
                                         self._dist.shardings()["rep"])
        self._rng_feature = np.random.RandomState(
            config.feature_fraction_seed & 0x7FFFFFFF)
        self._rec_layout = None  # lazy: packed split-record fetch plan
        # sampling-mask randomness lives ON DEVICE (bagging/GOSS/MVS
        # masks are computed in jitted ops; a host mask would ship
        # 4N bytes through the ~14 MB/s tunnel every iteration)
        self._bag_key = jax.random.PRNGKey(config.bagging_seed &
                                           0x7FFFFFFF)
        self._label_pos = None  # lazy device label>0 (pos/neg bagging)
        self._quant_key = (jax.random.PRNGKey(
            config.data_random_seed & 0x7FFFFFFF)
            if self.grow_params.quantize else None)
        if objective is not None:
            objective.init(train_set.metadata, n)

        # ---- observability -------------------------------------------
        # tier/gate decision record: which fast tier every tree of this
        # booster runs on, and the gate that rejected each higher tier
        # (utils/telemetry.py; the round-4/5 regressions were all
        # invisible because this was only derivable from profiler runs)
        self.tier_decision = self._tier_gates(
            config, use_pallas=use_pallas, dist_active=dist_active,
            learner=learner, num_shards=num_shards, wave_on=wave_on,
            two_col=two_col, refine_shift=refine_shift, any_cat=any_cat,
            any_missing=any_missing, use_pool=use_pool,
            forced=bool(forced), G_cols=G_cols,
            split_kernel=split_kernel, split_gate=split_gate)
        self._collective_per_pass = 0
        self._collective_ops_per_pass = 0
        self._collective_per_axis = {}
        if dist_active and self._dist is not None:
            from ..ops.grow import collective_bytes_per_pass
            # the builder's params carry the real DistConfig (the
            # booster-level grow_params keeps the serial default)
            est = collective_bytes_per_pass(self._dist.params,
                                            self._F_pad, self._n_pad)
            self._collective_per_pass = est["total"]
            self._collective_ops_per_pass = est["ops"]
            self._collective_per_axis = est.get("per_axis", {})
        self._telemetry = None
        self._tele_counters_last: Dict[str, float] = {}
        if getattr(config, "telemetry_file", ""):
            self.attach_telemetry(config.telemetry_file)
        else:
            # a process-default recorder (set by the continual daemon /
            # CLI via telemetry.set_recorder) adopts every booster it
            # outlives: one JSONL stream for a whole ingest->train->
            # publish loop instead of one file handle per batch
            from ..utils import telemetry as _tele_mod
            if _tele_mod.get_recorder() is not None:
                self.attach_telemetry(_tele_mod.get_recorder())
        if self._stream_upload:
            # the streamed construction finished before the recorder
            # attached: publish the upload's prefetch-overlap stats
            # now (the ingest/prefetch record obs/rules.py watches)
            from ..utils import telemetry as _tele_mod
            rec = self._telemetry or _tele_mod.get_recorder()
            if rec is not None:
                rec.emit("ingest", event="prefetch",
                         **self._stream_upload)

    # ------------------------------------------------------------------
    def _constraint_tuples(self, config: Config, train_set: TpuDataset,
                           F: int):
        """Static per-feature (monotone, penalty) tuples padded to the
        device feature width.  Config lists are indexed by ORIGINAL
        column (config.h:357 monotone_constraints, feature_contri);
        remap through used_features and pad with neutral values."""
        pad = self._F_pad
        mono = ()
        if config.monotone_constraints:
            mc = list(config.monotone_constraints)
            vals = [int(mc[i]) if i < len(mc) else 0
                    for i in train_set.used_features]
            if any(vals):
                mono = tuple(vals + [0] * (pad - F))
        pen = ()
        if config.feature_contri:
            fc = list(config.feature_contri)
            vals = [float(fc[i]) if i < len(fc) else 1.0
                    for i in train_set.used_features]
            if any(v != 1.0 for v in vals):
                pen = tuple(vals + [1.0] * (pad - F))
        return mono, pen

    def _forced_splits(self, config: Config, train_set: TpuDataset,
                       dist_active: bool):
        """BFS-flattened forced splits from ``forcedsplits_filename``
        (``ForceSplits``, serial_tree_learner.cpp:544): JSON nodes
        {feature, threshold, left, right} become (leaf_id,
        inner_feature, threshold_bin) triples in the order the growth
        loop will apply them (left child keeps the parent's leaf id,
        right child gets id t+1 at iteration t)."""
        fname = config.forcedsplits_filename
        if not fname:
            return ()
        if dist_active:
            Log.warning("forced splits are not supported by parallel "
                        "tree learners; ignoring %s", fname)
            return ()
        import json as _json
        with open(fname) as f:
            root = _json.load(f)
        out = []
        queue = [(root, 0)]
        t = 0
        while queue and t < config.num_leaves - 1:
            node, leaf = queue.pop(0)
            real_f = int(node["feature"])
            inner = train_set.inner_feature_index(real_f)
            if inner is None or inner < 0:
                Log.warning("forced split on unused feature %d; "
                            "stopping forced splits", real_f)
                break
            mapper = train_set.mappers[real_f]
            thr_bin = int(np.asarray(mapper.value_to_bin(
                np.asarray([float(node["threshold"])]))).reshape(-1)[0])
            out.append((leaf, inner, thr_bin))
            if node.get("left"):
                queue.append((node["left"], leaf))
            if node.get("right"):
                queue.append((node["right"], t + 1))
            t += 1
        return tuple(out)

    # ------------------------------------------------------------------
    def _tier_gates(self, config, use_pallas, dist_active, learner,
                    num_shards, wave_on, two_col, refine_shift, any_cat,
                    any_missing, use_pool, forced, G_cols,
                    split_kernel="xla", split_gate=None):
        """The histogram-tier decision for this booster, with the gate
        that rejected each higher tier.  Mirrors the driver gates above
        and the routed-kernel feasibility in ``ops/grow.py`` — the
        telemetry contract is that a reader can tell WHY a run landed
        on a slower tier without rerunning it under a profiler."""
        from ..ops.histogram import routed_chunk_ok
        gates = {}
        quantize = int(self.grow_params.quantize)
        speculate = int(self.grow_params.speculate)
        if not two_col:
            if not config.use_quantized_grad:
                gates["two_col"] = "use_quantized_grad=false"
            elif not wave_on:
                gates["two_col"] = "wave growth off"
            elif self._bundles is not None:
                gates["two_col"] = ("EFB bundles active "
                                    "(FixHistogram reads counts)")
            elif any_cat:
                gates["two_col"] = ("categorical scans read real counts "
                                    "(cnt_ok, min_data_per_group)")
            elif config.min_data_in_leaf > 1:
                gates["two_col"] = "min_data_in_leaf > 1 needs counts"
            else:
                gates["two_col"] = "min_sum_hessian_in_leaf <= 0"
        if not wave_on:
            if not config.wave_splits:
                gates["wave"] = "wave_splits=false"
            elif learner == "data2d":
                gates["wave"] = ("data2d runs the non-wave per-axis "
                                 "collective schedule")
            elif not use_pool:
                gates["wave"] = ("histogram pool over budget "
                                 "(histogram_pool_size)")
            else:
                gates["wave"] = "forced splits"
        if refine_shift == 0:
            if not config.hist_refinement:
                gates["c2f"] = "hist_refinement=false"
            elif not wave_on:
                gates["c2f"] = "wave growth off"
            elif dist_active and learner != "data":
                gates["c2f"] = f"tree_learner={learner}"
            elif self._bundles is not None:
                gates["c2f"] = "EFB bundles active"
            elif any_cat:
                gates["c2f"] = "categorical features"
            elif self.max_bin < 48:
                gates["c2f"] = f"max_bin={self.max_bin} < 48"
            else:
                gates["c2f"] = ("stream below the per-pass fixed-cost "
                                "break-even (features x bins < ~7000)")
        # routed-kernel feasibility (ops/grow.py routed_full_ok /
        # routed_coarse_ok — the in-pass routing tier)
        if not use_pallas:
            gates["routed"] = "cpu backend (segsum histograms)"
        elif self._bundles is not None:
            gates["routed"] = "EFB bundles active"
        elif any_cat:
            gates["routed"] = "categorical splits need bin masks"
        elif learner == "feature":
            gates["routed"] = ("feature-parallel: split column lives "
                               "on one shard")
        routed = "routed" not in gates and routed_chunk_ok(
            self.max_bin, G_cols, 128,
            int(config.tpu_rows_per_block))
        if "routed" not in gates and not routed:
            gates["routed"] = "feature block exceeds one kernel chunk"
        # best-split engine gate (split_kernel): why a run scans splits
        # in XLA instead of the fused/standalone Pallas kernels —
        # triage_run.py flags the silent-fallback-on-TPU case
        if split_kernel != "pallas" and split_gate:
            gates["split"] = split_gate
        if two_col:
            tier = "two_col"
        elif wave_on:
            tier = "wave_quant" if quantize else "wave"
        elif speculate:
            tier = "speculative"
        else:
            tier = "exact"
        return {
            "tier": tier,
            "gates": gates,
            "split_kernel": split_kernel,
            "routed": bool(routed),
            "c2f": bool(refine_shift),
            "refine_shift": int(refine_shift),
            "quantize": quantize,
            "speculate": speculate,
            "wave": bool(wave_on),
            "hist_impl": self.grow_params.hist_impl,
            "use_hist_pool": bool(use_pool),
            "efb_groups": (int(self._bundles.num_groups)
                           if self._bundles is not None else 0),
            "learner": learner if dist_active else "serial",
            "num_shards": int(num_shards) if dist_active else 1,
            "mesh_shape": ([int(s) for s in
                            self._dist.mesh.devices.shape]
                           if dist_active and self._dist is not None
                           else [1]),
        }

    # ------------------------------------------------------------------
    def attach_telemetry(self, target):
        """Attach a run recorder (``utils/telemetry.py``): a JSONL path
        or an existing :class:`RunRecorder`.  Idempotent — the first
        attachment wins.  Works on loaded (predict-only) boosters too.
        """
        from ..utils import telemetry
        if getattr(self, "_telemetry", None) is not None:
            return self._telemetry
        if isinstance(target, telemetry.RunRecorder):
            rec = target
            rec.emit("run_start", **self._run_info())
        else:
            rec = telemetry.RunRecorder(str(target),
                                        run_info=self._run_info())
        self._telemetry = rec
        self._tele_counters_last = telemetry.counters_snapshot()
        return rec

    def telemetry_summary(self):
        rec = getattr(self, "_telemetry", None)
        return rec.summary() if rec is not None else None

    def _run_info(self):
        """Backend identity + config subset for the run_start record."""
        cfg = self.config
        info = {
            "backend": "unknown",
            "tier": getattr(self, "tier_decision", None),
            "params": {
                "objective": cfg.objective,
                "num_leaves": cfg.num_leaves,
                "max_bin": cfg.max_bin,
                "num_class": cfg.num_class,
                "tree_learner": cfg.tree_learner,
                "use_quantized_grad": cfg.use_quantized_grad,
                "wave_splits": cfg.wave_splits,
                "hist_refinement": cfg.hist_refinement,
                "min_data_in_leaf": cfg.min_data_in_leaf,
            },
        }
        if self.train_set is not None:
            info["rows"] = int(self.num_data)
            info["features"] = int(self.num_features)
        try:
            import jax
            info["backend"] = jax.default_backend()
            dev = jax.local_devices()[0]
            info["device_kind"] = str(getattr(dev, "device_kind", ""))
            stats = dev.memory_stats()
            if stats:
                info["device_memory"] = {
                    k: int(stats[k]) for k in
                    ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                    if k in stats}
        except Exception:
            # backend identity must never take the run down — degraded
            # environments are exactly when telemetry matters most
            info["backend_degraded"] = True
        return info

    # ------------------------------------------------------------------
    def add_valid(self, name: str, raw: np.ndarray, metadata: Metadata,
                  binned: Optional[TpuDataset] = None):
        """Register a validation set.  When its aligned binned matrix is
        provided, per-iteration scoring runs on device by replaying the
        fresh tree's split records (:func:`~lightgbm_tpu.ops.grow.
        route_rows`) instead of a host tree traversal — O(1) host work
        per iteration."""
        import jax.numpy as jnp

        vs = ValidSet(name, raw, metadata)
        vs.score = np.zeros((self.num_tree_per_iteration, raw.shape[0]),
                            dtype=np.float64)
        if metadata.init_score is not None:
            vs.score += np.asarray(metadata.init_score).reshape(
                vs.score.shape[0], -1)
        # replay existing model (continue-train case)
        dt_leaf = np.uint8 if self.config.num_leaves <= 256 else np.uint16
        for i, tree in enumerate(self.models):
            if self._track_train_leaf:
                la = tree.predict_leaf_index(raw).astype(dt_leaf)
                vs.leaf_idx_per_tree.append(la)
                vs.score[i % self.num_tree_per_iteration] += \
                    tree.leaf_value[la.astype(np.int32)]
            else:
                vs.score[i % self.num_tree_per_iteration] += \
                    tree.predict(raw)
        if binned is not None and self.num_features > 0:
            if self._bundles is not None:
                xtv = self._bundles.bundle_matrix(binned.binned).T
            else:
                xtv = binned.binned.T  # (F, rows) narrow dtype
                xtv = np.pad(xtv,
                             ((0, self._F_pad - xtv.shape[0]), (0, 0)))
            vs.xt = jnp.asarray(xtv)  # narrow dtype on device
        self.valid_sets.append(vs)

    # ------------------------------------------------------------------
    def _feature_fraction_mask(self):
        import jax.numpy as jnp
        F = self.num_features
        frac = self.config.feature_fraction
        mask = np.zeros(self._F_pad, bool)
        if frac >= 1.0:
            mask[:F] = True
        else:
            k = max(1, int(frac * F))
            mask[self._rng_feature.choice(F, size=k, replace=False)] = True
        return jnp.asarray(mask)

    def _bagging_active(self) -> bool:
        cfg = self.config
        pos_neg = (cfg.pos_bagging_fraction < 1.0 or
                   cfg.neg_bagging_fraction < 1.0)
        return cfg.bagging_freq > 0 and (cfg.bagging_fraction < 1.0 or
                                         pos_neg)

    def _draw_bag_mask(self, it):
        """Pure device draw of the bernoulli/stratified bagging mask
        for (global) iteration ``it`` — ``it`` may be a host int or a
        traced scalar (the fused super-step folds it inside the scan).
        Keying the PRNG by the GLOBAL iteration — and running ONE
        jitted program from both the sequential and the scan-inlined
        call sites — makes the fused and sequential paths
        bit-identical."""
        import jax
        if getattr(self, "_trace_raw", False):
            # battery trace: ``self._bag_key`` is a per-model tracer,
            # so the draw must inline into the enclosing trace instead
            # of caching a jitted wrapper around it.  jit called under
            # a trace inlines to the same program as the raw call, so
            # this is program-identical to the solo path.
            self._ensure_label_pos()
            return self._draw_bag_mask_impl(it)
        if getattr(self, "_bag_draw_jit", None) is None:
            self._ensure_label_pos()
            self._bag_draw_jit = jax.jit(self._draw_bag_mask_impl)
        return self._bag_draw_jit(it)

    def _ensure_label_pos(self) -> None:
        """Materialize the label-sign vector for stratified bagging
        OUTSIDE any trace (a lazily-built device array created during
        tracing would cache a tracer on self)."""
        import jax.numpy as jnp
        cfg = self.config
        pos_neg = (cfg.pos_bagging_fraction < 1.0 or
                   cfg.neg_bagging_fraction < 1.0)
        if pos_neg and self._label_pos is None:
            self._label_pos = jnp.asarray(np.asarray(
                self.train_set.metadata.label)[:self.num_data] > 0)

    def _draw_bag_mask_impl(self, it):
        import jax
        import jax.numpy as jnp
        cfg = self.config
        pos_neg = (cfg.pos_bagging_fraction < 1.0 or
                   cfg.neg_bagging_fraction < 1.0)
        key = jax.random.fold_in(self._bag_key, it)
        u = jax.random.uniform(key, (self.num_data,))
        if pos_neg:
            # class-stratified bagging: positives/negatives sampled
            # at their own fractions
            return jnp.where(self._label_pos,
                             u < cfg.pos_bagging_fraction,
                             u < cfg.neg_bagging_fraction
                             ).astype(jnp.float32)
        return (u < cfg.bagging_fraction).astype(jnp.float32)

    def _bagging_mask(self, grad=None, hess=None):
        """Per-row sample weights for this iteration (0 = out of bag;
        non-0/1 weights rescale grad/hess, counts stay presence-based).
        Base class: bernoulli bagging every ``bagging_freq`` iterations
        (``GBDT::Bagging``, ``gbdt.cpp:182``); GOSS/MVS override using
        the gradient magnitudes.  Returns a DEVICE (N,) f32 vector —
        mask generation is jitted device work (a host mask means a 4N-
        byte upload per iteration through the tunnel)."""
        cfg = self.config
        if not self._bagging_active():
            return None
        if self.iter % cfg.bagging_freq == 0:
            self._cached_bag = self._draw_bag_mask(self.iter)
        return getattr(self, "_cached_bag", None)

    def _fused_mask_fn(self):
        """The sampling mask as a scan-capturable pure function
        ``(iter, prev_mask, grad, hess) -> mask`` for the fused
        super-step, or None when no sampling applies.  Base class:
        bernoulli/stratified bagging — redraw on ``bagging_freq``
        boundaries, carry the previous mask otherwise (exactly
        :meth:`_bagging_mask`'s cache semantics, with the cache as the
        scan carry).  GOSS/MVS override (models/boosting.py): their
        masks are pure functions of the iteration's gradients."""
        import jax
        if not self._bagging_active():
            return None
        self._ensure_label_pos()
        freq = self.config.bagging_freq

        def fn(it, prev, grad, hess):
            return jax.lax.cond(it % freq == 0, self._draw_bag_mask,
                                lambda _: prev, it)
        return fn

    # ------------------------------------------------------------------
    def _pipeline_ok(self) -> bool:
        """Pipelined boosting applies when nothing needs the host tree
        within the iteration: single tree per iteration, no validation
        scoring, no per-tree leaf tracking (DART) and no objective leaf
        renewal hook — then the newest tree's record fetch can hide
        behind the NEXT tree's device build."""
        return (self._pipeline_enabled and
                self.num_tree_per_iteration == 1 and
                not self.valid_sets and not self._track_train_leaf and
                self.objective is not None and self.num_features > 0 and
                type(self.objective).renew_tree_output
                is Objective.renew_tree_output)

    # ---- fused boosting super-steps ----------------------------------
    # One jitted ``lax.scan`` runs K = config.fused_iters boosting
    # iterations entirely on device — objective gradients, the
    # bagging/GOSS/MVS mask draw (PRNG key folded by GLOBAL iteration
    # inside the scan), ``build_tree`` and the score update — with the
    # (score, bagging-mask) carry donated.  The stacked (K, ...) split
    # records come back in ONE packed device->host transfer and are
    # materialized into K Trees up front; train_one_iter then serves
    # them one per call, so the external one-iteration-per-update
    # contract (engine loop, callbacks, num_boost_round counting) is
    # unchanged while Python dispatch and tunnel round-trips drop from
    # O(iterations) to O(iterations / K).  Both GPU-GBDT systems we
    # track keep the iteration resident on the accelerator the same
    # way (arXiv:1806.11248; arXiv:1706.08359).  Bit-exact with the
    # sequential (pipelined) path: same ops in the same order, the
    # same PRNG folds, and the same host-RNG feature-fraction draws
    # (pre-drawn per block in sequential order).

    def _fused_ok(self) -> bool:
        """Super-step eligibility.  Anything that needs the host tree,
        per-iteration scores, or per-iteration host randomness beyond
        the pre-drawn feature masks falls back to the per-iteration
        path: custom objectives (grad is checked at the call site),
        leaf-renewal objectives, multi-model-per-iteration objectives,
        DART/RF (``_superstep_enabled``), attached validation sets and
        training metrics (their eval cadence — including early
        stopping — reads scores every iteration).  Distributed
        learners (data/feature/voting) FUSE: the same K-iteration scan
        runs SPMD under ``shard_map`` over the learner's mesh, with
        the strategy collectives inside the one compiled program
        (:meth:`_build_superstep_fn`)."""
        cfg = self.config
        return (self._superstep_enabled and cfg.fused_iters > 1 and
                self.num_tree_per_iteration == 1 and
                not self.valid_sets and not self._track_train_leaf and
                self.objective is not None and
                self.num_features > 0 and
                not cfg.is_provide_training_metric and
                type(self.objective).renew_tree_output
                is Objective.renew_tree_output and
                self.objective.gradient_fn() is not None)

    def _fused_bias_pending(self) -> bool:
        """True when the NEXT iteration is the boost_from_average
        iteration 0 — it mutates the score from host state and the
        first tree absorbs the bias, so it runs unfused (the pipelined
        path); fusion engages from iteration 1."""
        return (self.iter == 0 and self.config.boost_from_average and
                not self._models and self._pending is None and
                self.train_set.metadata.init_score is None)

    def _superstep_core(self, batched: bool = False):
        """The raw (unjitted, unsharded) K-iteration scan body, shared
        by the solo fused path (:meth:`_build_superstep_fn`) and the
        many-model battery trainer (``models/battery.py``).

        With ``batched=True`` the returned callable grows two trailing
        per-model arguments — ``wvec``, a per-row gradient/hessian
        weight (the battery's CV fold masks ride here, multiplying
        exactly where solo weighted training multiplies metadata
        weights), and ``bag_key``, the bagging/GOSS/MVS PRNG key that
        replaces the closure-captured ``self._bag_key`` — so the whole
        scan can be lifted over a leading model axis with ``jax.vmap``.
        Per-model values arrive TRACED while every structural knob
        stays static (the bit-exactness anchor: a traced operand of
        equal value yields the same elementwise ops as a constant, but
        a static knob becoming traced would change the expression
        tree).  The tracer swap happens at trace time only, and
        ``_trace_raw`` routes the mask draws to their raw impls so no
        jitted wrapper captures a tracer in its closure."""
        import jax
        import jax.numpy as jnp
        from ..ops.grow import build_tree_impl
        from ..ops.lookup import take_small

        dist = self._dist
        p = self.grow_params if dist is None else dist.params
        n, n_pad = self.num_data, self._n_pad
        obj = self.objective
        grad_fn = self.objective.gradient_fn()
        mask_fn = self._fused_mask_fn()
        self._fused_has_bagging = mask_fn is not None
        bundle_maps = self._bundle_maps
        quantize = bool(p.quantize)
        li_dt = jnp.uint8 if self.config.num_leaves <= 255 else jnp.uint16
        # keys the host never reads stay on device (leaf_idx is kept
        # separately, narrow, for the exact rewind/rollback replay)
        drop = ("leaf_idx", "leaf_values", "leaf_values_final",
                "leaf_stats")
        rows_sharded = dist is not None and dist.kind in ("data",
                                                          "voting",
                                                          "data2d")
        if rows_sharded:
            # data2d shards rows over the ROW axis only (R of the R*F
            # devices); the 1-D learners' row axis is the whole mesh
            ax = dist.params.dist.axis
            n_loc = n_pad // dist.row_shards

        pager_view = getattr(self, "_pager_view", None)

        def superstep(score, bag0, lr, quant_key, xt, base_mask,
                      num_bins, missing_type, is_cat, iters, fmasks,
                      tree_ids, *extras):
            if pager_view is not None:
                # paged lane: the xt operand is a replicated dummy —
                # the scan reads the matrix through page callbacks
                # (trace-time swap; the scan body is otherwise
                # IDENTICAL to the resident one, which is what makes
                # paged-vs-resident byte-parity structural)
                xt = pager_view
            if batched:
                wvec, bag_key = extras
                saved_key = self._bag_key
                saved_raw = getattr(self, "_trace_raw", False)
                self._bag_key = bag_key
                self._trace_raw = True

            def step(carry, xs):
                sc, bag_prev = carry
                it, fmask, tid = xs
                if batched:
                    # per-model fold/sample weights multiply inside
                    # the objective exactly where solo weighted
                    # training multiplies metadata weights
                    # (objectives.py ``_w``/``_jitted_gradients``) —
                    # the loop-of-solo CV reference's op order
                    with obj.weight_override(wvec):
                        grad, hess = obj.get_gradients(sc)
                else:
                    grad, hess = grad_fn(sc)
                grad = jnp.atleast_2d(grad)
                hess = jnp.atleast_2d(hess)
                bag = mask_fn(it, bag_prev, grad, hess) \
                    if mask_fn is not None else None
                gp = jnp.pad(grad[0].astype(jnp.float32), (0, n_pad - n))
                hp = jnp.pad(hess[0].astype(jnp.float32), (0, n_pad - n))
                w = None
                if bag is not None:
                    w = jnp.pad(jnp.asarray(bag, jnp.float32).reshape(-1),
                                (0, n_pad - n))
                    gp = gp * w
                    hp = hp * w
                if rows_sharded:
                    # the full-N weighted gradients are computed
                    # replicated (bit-identical to the serial scan),
                    # then each shard slices ITS contiguous row block
                    # for the local histogram pass; base_mask arrives
                    # already local via its in_spec
                    off = jax.lax.axis_index(ax) * n_loc
                    gp_b = jax.lax.dynamic_slice_in_dim(gp, off, n_loc)
                    hp_b = jax.lax.dynamic_slice_in_dim(hp, off, n_loc)
                    mask_b = base_mask
                    if w is not None:
                        mask_b = mask_b * (jax.lax.dynamic_slice_in_dim(
                            w, off, n_loc) > 0)
                else:
                    gp_b, hp_b = gp, hp
                    mask_b = base_mask if w is None \
                        else base_mask * (w > 0)
                kw = {}
                if quantize:
                    kw["quant_key"] = jax.random.fold_in(quant_key, tid)
                if bundle_maps is not None:
                    kw["bundle_maps"] = bundle_maps
                rec = build_tree_impl(xt, gp_b, hp_b, mask_b, fmask,
                                      num_bins, missing_type, is_cat, p,
                                      **kw)
                vals = rec["leaf_values_final"] * lr
                li = rec["leaf_idx"]
                if rows_sharded:
                    # the score delta is computed on the shard's OWN
                    # rows (take_small's select chain is the per-row
                    # cost) and ONE tiled all-gather rebuilds the
                    # global (N,) update — per-shard work stays
                    # O(N/D) and the gather's per-shard wire
                    # contribution is a constant n_loc*4 bytes at any
                    # mesh size.  The gather preserves contiguous row
                    # order, so the adds land per row exactly as in
                    # the serial scan (bit-parity)
                    upd = jax.lax.all_gather(take_small(vals, li), ax,
                                             tiled=True)[:n]
                else:
                    li = li[:n]
                    upd = take_small(vals, li)
                new_sc = sc.at[0].add(upd)
                host_rec = {k: v for k, v in rec.items()
                            if k not in drop}
                # numerical-health flag: non-finite gradients, leaf
                # values or scores ride the existing packed block
                # fetch (zero extra device calls).  Gradients must be
                # checked too — NaN gradients kill every split gain
                # and masquerade as a legitimate "no splittable leaf"
                # stop, which would end training silently instead of
                # loudly (utils/health.py)
                host_rec["nonfinite"] = jnp.logical_not(
                    jnp.all(jnp.isfinite(grad[0])) &
                    jnp.all(jnp.isfinite(vals)) &
                    jnp.all(jnp.isfinite(new_sc)))
                new_bag = bag if bag is not None else bag_prev
                return (new_sc, new_bag), \
                    (host_rec, li.astype(li_dt), vals)

            try:
                (final_sc, final_bag), (recs, leaf_idx_k, vals_k) = \
                    jax.lax.scan(step, (score, bag0),
                                 (iters, fmasks, tree_ids))
            finally:
                if batched:
                    # the key/raw swap is trace-time state only —
                    # restore it even when the trace aborts (e.g. a
                    # kernel without a batching rule under vmap)
                    self._bag_key = saved_key
                    self._trace_raw = saved_raw
            # returning the donated inputs forces XLA to copy the
            # block-start score AND bagging mask out — the
            # rewind/rollback anchor at no extra dispatch, and (under
            # async pipelining) the un-donated value the PREVIOUS
            # block's commit reads after ITS outputs were donated to
            # this dispatch
            return (score, bag0, final_sc, final_bag, recs, leaf_idx_k,
                    vals_k)

        return superstep

    def _build_superstep_fn(self):
        """Build the jitted K-iteration scan.  K is carried by the xs
        shapes, so one jitted callable serves every block size (the
        shorter tail block recompiles once).  Big device residents
        (the binned matrix, masks, descriptors) ride as ARGUMENTS —
        closure capture would embed them in the remote-compile
        payload; the objective's label tensors stay closure-captured
        because ``gradient_fn`` owns them.

        With a distributed learner the SAME scan body runs SPMD: the
        whole K-iteration program is wrapped in ``shard_map`` over the
        learner's 1-D mesh, the binned matrix arrives as the local
        shard (rows for data/voting, features for feature-parallel),
        and the per-strategy histogram/merge collectives inside
        ``build_tree_impl`` ride within the one compiled program — K
        iterations of sharded build+update cost ONE dispatch, not 5K
        per-shard dispatches.  Gradients, mask draws and the score
        update run replicated (identical math on every shard — the
        bit-exactness anchor against the serial scan), and the
        row-sharded learners all-gather the (N,) leaf assignment once
        per iteration for the replicated score update."""
        import jax

        superstep = self._superstep_core()
        dist = self._dist
        rows_sharded = dist is not None and dist.kind in ("data",
                                                          "voting",
                                                          "data2d")
        if dist is not None:
            from jax.sharding import PartitionSpec as P
            from ..parallel.learners import shard_map_compat
            ax_name = dist.params.dist.axis
            R = P()
            if dist.kind == "feature":
                # features sharded: xt + descriptors + the stacked
                # per-iteration feature masks split over the feature
                # axis; rows (and the score carry) replicated
                in_specs = (R, R, R, R, P(ax_name, None), R,
                            P(ax_name), P(ax_name), P(ax_name), R,
                            P(None, ax_name), R)
            elif dist.kind == "data2d":
                # 2-D: rows down the data axis (base_mask local),
                # feature tiles + descriptors + the stacked feature
                # masks across the feature axis; the score carry and
                # gradients stay replicated
                fax = dist.feat_axis
                in_specs = (R, R, R, R, P(fax, ax_name), P(ax_name),
                            P(fax), P(fax), P(fax), R,
                            P(None, fax), R)
            else:   # data | voting: rows sharded, features whole
                in_specs = (R, R, R, R, P(None, ax_name), P(ax_name),
                            R, R, R, R, R, R)
            # outputs are replicated by construction — split records/
            # merges are strategy-replicated, the score delta is
            # re-gathered in-step — EXCEPT the stacked per-iteration
            # leaf assignment of the row-sharded learners: each shard
            # emits its local (K, n_loc) block and the out_spec
            # stitches the global (K, n_pad) table with no collective
            # (the host-side rewind replay is its only reader)
            li_spec = P(None, ax_name) if rows_sharded else R
            if self._pager is not None:
                # paged: the xt slot carries a replicated dummy; each
                # program instance pages its OWN (f_loc, n_loc) block
                # via axis-indexed callbacks instead of receiving a
                # sharded operand
                in_specs = in_specs[:4] + (R,) + in_specs[5:]
            superstep = shard_map_compat(superstep, dist.mesh,
                                         in_specs=in_specs,
                                         out_specs=(R, R, R, R, R,
                                                    li_spec, R))

        # carry donation frees both N-sized buffers for in-place reuse
        # on device; CPU XLA has no donation and would warn per call
        donate = (0, 1) if jax.default_backend() not in ("cpu",) else ()
        return jax.jit(superstep, donate_argnums=donate)

    def _pipeline_depth(self) -> int:
        """Extra fused blocks kept in flight beyond the one being
        landed (``superstep_pipeline_depth``); 0 = dispatch-then-fetch
        (the pre-pipelining behavior)."""
        return max(int(getattr(self.config, "superstep_pipeline_depth",
                               0) or 0), 0)

    def _next_dispatch_iter(self) -> int:
        """First iteration of the next block to dispatch: the frontier
        of the in-flight queue, or the served boundary when nothing is
        outstanding."""
        if self._sq:
            last = self._sq[-1]
            return last["i0"] + last["k"]
        return self.iter

    def _dispatch_superstep_block(self, elastic_alive,
                                  required: bool) -> bool:
        """Dispatch ONE fused block at the queue frontier and append
        it to the in-flight queue (dispatched, unfetched).  Returns
        False without dispatching when the frontier is at/past the
        ``num_iterations`` horizon and the block is speculative
        (``required=False``) — the pipeline never wastes device work
        past the end of training."""
        import time as _time

        import jax
        import jax.numpy as jnp
        from ..utils import telemetry as _telemetry
        from ..utils.profiling import timed

        cfg = self.config
        i0 = self._next_dispatch_iter()
        K = int(cfg.fused_iters)
        remaining = cfg.num_iterations - i0
        if remaining <= 0 and not required:
            return False
        if 0 < remaining < K:
            # auto-size the tail block down to the num_iterations
            # boundary (shorter scan -> one extra XLA compile there,
            # which triage_run treats as per-shape warmup)
            K = remaining
        # elastic dispatch fence: the ONLY host state a fused dispatch
        # consumes before its fetch lands is the feature-fraction RNG
        # stream and the quantization-stream position — when the
        # dispatch is abandoned (hung collective) or dies (shard
        # loss), abort_inflight_dispatch restores exactly these
        # (parallel/elastic.py recovery path).  With blocks in flight
        # the LIVE fence is always the OLDEST outstanding dispatch's
        # pre-state: restoring it rewinds across EVERY queued block's
        # RNG/quantization-stream consumption in one step.
        fence = {"rng_state": self._rng_feature.get_state(),
                 "tid": self._trees_dispatched}
        if self.__dict__.get("_dispatch_fence") is None:
            self._dispatch_fence = fence
        with timed("superstep/dispatch"):
            # host feature-fraction draws consumed in sequential order
            fmasks = jnp.stack([self._feature_fraction_mask()
                                for _ in range(K)])
            iters = jnp.arange(i0, i0 + K, dtype=jnp.int32)
            tree_ids = jnp.arange(self._trees_dispatched,
                                  self._trees_dispatched + K,
                                  dtype=jnp.int32)
            self._trees_dispatched += K
            if self._superstep_jit is None:
                self._superstep_jit = self._build_superstep_fn()
            if self._sq:
                # chain on the in-flight predecessor's device futures:
                # the score/bag carries never touch the host between
                # blocks, and this dispatch goes out BEFORE the
                # predecessor's fetch
                prev = self._sq[-1]["outs"]
                score0, bag0 = prev[2], prev[3]
            else:
                score0 = self._score
                bag0 = getattr(self, "_cached_bag", None)
                if bag0 is None:
                    # ALL-ONES sentinel: with no cached mask the
                    # sequential path trains UNBAGGED until the next
                    # bagging_freq boundary (continue-training starts
                    # mid-cycle), and a unit weight vector is
                    # bit-identical to "no mask" (x*1.0 == x); a zeros
                    # sentinel would silently zero every gradient
                    # until the first in-block draw
                    bag0 = jnp.ones(self.num_data, jnp.float32)
            qk = self._quant_key if self._quant_key is not None \
                else jax.random.PRNGKey(0)
            _telemetry.counters.incr("superstep_dispatches")
            if self._dist is not None:
                from ..utils import faults as _faults
                # fired once per fused-block dispatch: the injected
                # stand-in for a shard dying or wedging inside the
                # block's collectives (tools/chaos_elastic.py)
                fault_mode = _faults.fire("mesh.collective")
                if fault_mode:
                    self._mesh_collective_fault(fault_mode,
                                                elastic_alive)
            outs = self._superstep_jit(
                score0, bag0, jnp.float32(self.shrinkage_rate), qk,
                self._xt, self._base_mask, self._num_bins,
                self._missing_type, self._is_cat, iters, fmasks,
                tree_ids)
        # an abandoned attempt (elastic stall watchdog moved on and a
        # re-mesh owns ``self`` now) must not commit ANY state — the
        # checks bracket every device interaction
        self._abandoned_check(elastic_alive)
        self._sq.append({"outs": outs, "i0": i0, "k": K,
                         "fence": fence, "lr": self.shrinkage_rate,
                         "t_dispatch": _time.perf_counter()})
        return True

    def _discard_queue(self) -> None:
        """Drop every dispatched-but-unfetched block and restore the
        host state their dispatches consumed (feature-fraction RNG
        draws, quantization-stream positions) — the pipelined half of
        the dispatch-fence contract.  The drain points are exactly
        the boundaries that already force one: the no-split stop, a
        learning-rate change, eligibility drift, rollback/rewind,
        a numerical-health trip, elastic abort/re-mesh."""
        if not self._sq:
            return
        first = self._sq[0]
        self._sq = []
        self._rng_feature.set_state(first["fence"]["rng_state"])
        self._trees_dispatched = int(first["fence"]["tid"])
        self.__dict__.pop("_dispatch_fence", None)

    def _recompute_bag_cache(self) -> None:
        """Rebuild the bernoulli/stratified bagging-mask cache from
        its defining PRNG fold at the CURRENT iteration — the one
        recipe shared by the fused-rewind restore and the pipeline
        drain (a drained queue may have donated the cached device
        buffer to an abandoned dispatch)."""
        cfg = self.config
        if not (self._fused_has_bagging and
                type(self)._bagging_mask is GBDT._bagging_mask):
            return
        it = self.iter
        if it > 0:
            last_draw = (it - 1) // cfg.bagging_freq * cfg.bagging_freq
            self._cached_bag = self._draw_bag_mask(last_draw)
        else:
            self.__dict__.pop("_cached_bag", None)

    def _train_superstep(self) -> bool:
        """One fused-super-step update: top up the in-flight dispatch
        queue (block K+1 goes out BEFORE block K's stacked records are
        fetched, so the one device->host round-trip per block hides
        behind the next block's device compute), then land the oldest
        block and serve its first tree.  The healthy-path device-call
        budget stays 2 per K-block at any pipeline depth — pipelining
        reorders the same dispatch+fetch pair, it never adds calls."""
        self._flush_pending()
        if self._stop_flag:
            return True
        # THIS attempt's generation token, captured before any device
        # work: a later retry overwrites the attribute with its own
        # token, and an abandoned zombie checking the shared attribute
        # instead of its captured one would see the RETRY's (alive)
        # token and commit phantom state
        elastic_alive = getattr(self, "_elastic_alive", None)
        self._elastic_beat()
        if self._sq and self._sq[0]["lr"] != self.shrinkage_rate:
            # a learning_rates schedule changed the shrinkage since
            # the queued blocks were dispatched: they were built at
            # the old rate — drain and redispatch at the new one
            # (BEFORE topping up, so no fresh block chains onto a
            # stale carry)
            self._discard_queue()
        target = 1 + self._pipeline_depth()
        while len(self._sq) < target:
            if not self._dispatch_superstep_block(
                    elastic_alive, required=not self._sq):
                break
        return self._land_superstep_block(elastic_alive)

    def _land_superstep_block(self, elastic_alive) -> bool:
        """Fetch + materialize the OLDEST in-flight block (the K'
        trees materialize from a single stacked fetch) and serve its
        first tree."""
        import time as _time

        from ..utils import telemetry as _telemetry
        from ..utils.profiling import timed

        entry = self._sq.pop(0)
        K = entry["k"]
        i0 = entry["i0"]
        rng_state = entry["fence"]["rng_state"]
        start_tid = int(entry["fence"]["tid"])
        t_fetch0 = _time.perf_counter()
        with timed("superstep/fetch"):
            # the block's ONE device->host transfer (packed f32)
            _telemetry.counters.incr("superstep_fetches")
            host = self._fetch_records(entry["outs"][4])
        self._abandoned_check(elastic_alive)
        # the live fence moves to the next outstanding dispatch (or
        # clears): this block is fetched, its state commits below
        if self._sq:
            self._dispatch_fence = self._sq[0]["fence"]
        else:
            self.__dict__.pop("_dispatch_fence", None)
        # per-block heartbeat: rides the block bookkeeping the
        # superstep telemetry record is assembled from — zero extra
        # device calls (parallel/elastic.py)
        self._elastic_beat(block=True)
        (start_score, _start_bag, final_score, final_bag, _recs,
         leaf_idx_k, vals_k) = entry["outs"]
        bad = np.asarray(host.pop("nonfinite", np.zeros(K)), bool)
        if np.any(bad):
            # the per-iteration health flag tripped: rewind to the
            # served boundary (nothing from this block — or the
            # queued blocks chained on it — was served or applied to
            # the score; only dispatch bookkeeping moved) and fail
            # loudly instead of serving a NaN model.  A finite stop
            # tree BEFORE the first bad iteration wins: post-stop
            # scan iterations are phantom state the replay discards
            # anyway.
            j = int(np.argmax(bad))
            stops = np.nonzero(np.asarray(host["n_leaves"])[:K] <= 1)[0]
            if stops.size == 0 or j <= int(stops[0]):
                self._sq = []
                self.__dict__.pop("_dispatch_fence", None)
                self._trees_dispatched = start_tid
                self._rng_feature.set_state(rng_state)
                from ..utils.health import abort_nonfinite
                abort_nonfinite(getattr(self, "_telemetry", None),
                                i0 + j, "superstep",
                                f"fused block of {K} starting at "
                                f"iteration {i0}")
        with timed("superstep/to_tree"):
            n_leaves_k = host["n_leaves"]
            trees, stop_idx = [], None
            for t in range(K):
                if int(n_leaves_k[t]) <= 1:
                    # constant stop tree; its init bias is always 0
                    # here (iteration 0 runs unfused) and its score
                    # contribution inside the scan was gated to 0
                    trees.append(Tree(2))
                    stop_idx = t
                    break
                rec_t = {k: v[t] for k, v in host.items()}
                tree = self._records_to_tree(rec_t)
                tree.apply_shrinkage(entry["lr"])
                trees.append(tree)
        if "n_arm_passes" in host:
            passes = host["n_arm_passes"][:len(trees)]
            self.last_arm_passes = int(passes[-1])
            hist_passes = int(np.sum(passes)) + len(trees)
        else:
            hist_passes = None
        self._fused_block = {
            "start_score": start_score, "start_iter": i0,
            "start_tid": start_tid, "rng_state": rng_state,
            "trees": trees, "stop_idx": stop_idx,
            "leaf_idx": leaf_idx_k, "vals": vals_k, "served": 0,
            # the shrinkage the block's trees were built with: a
            # learning_rates schedule (reset_parameter callback)
            # changing it mid-block invalidates the unserved trees
            "lr": entry["lr"],
        }
        if stop_idx is None:
            if self._sq:
                # this block's own final score/bag buffers were
                # DONATED to the next queued dispatch; commit the
                # bit-identical copies that dispatch returned of its
                # inputs instead
                self._score = self._sq[0]["outs"][0]
                if self._fused_has_bagging:
                    self._cached_bag = self._sq[0]["outs"][1]
            else:
                self._score = final_score
                if self._fused_has_bagging:
                    self._cached_bag = final_bag
        else:
            # the scan has no early exit: iterations AFTER the stop
            # tree still ran, and under bagging their fresh draws can
            # even split — those phantom contributions (and the
            # post-stop bagging mask) must not leak into the
            # model-consistent state.  Queued successor blocks are
            # phantom state wholesale: discard them (restoring their
            # consumed RNG draws), then replay the pre-stop prefix
            # (the stop tree itself contributes 0).
            self._discard_queue()
            self._score, _ = self._fused_replay_score(stop_idx)
        # superstep telemetry marker (consumed by train_one_iter).
        # fetch_overlap_s: wall between this block's dispatch and its
        # fetch — the window its device compute overlapped host work
        # (serving the previous block, materializing its trees,
        # dispatching the successor).  ~0 at depth 0 by construction.
        self._tele_superstep = {
            "k": K, "hist_passes": hist_passes,
            "pipeline_depth": self._pipeline_depth(),
            "fetch_overlap_s": round(
                max(t_fetch0 - entry["t_dispatch"], 0.0), 6),
        }
        if self._dist is not None:
            # per-block collective accounting for the sharded scan:
            # static per-pass estimate x passes in the block, plus the
            # once-per-iteration leaf-assignment all-gather of the
            # row-sharded learners
            hp = hist_passes if hist_passes is not None \
                else K * max(self.config.num_leaves, 1)
            extra_b = extra_o = 0
            if self._dist.kind in ("data", "voting", "data2d"):
                # per-SHARD send payload of the tiled leaf-assignment
                # all-gather — n_loc*4 bytes, O(1) in mesh size at
                # fixed rows/shard (collective_bytes_per_pass is a
                # per-shard estimate; mixing in the gathered GLOBAL
                # width would make the telemetry read as if wire cost
                # grew with the mesh)
                n_loc = self._n_pad // self._dist.row_shards
                extra_b, extra_o = K * n_loc * 4, K
            # per-AXIS attribution (obs/rules.py keys its weak-scaling
            # anomaly on these): 1-D learners put everything on their
            # single axis; data2d splits histogram traffic (row axis)
            # from merge+routing (feature axis).  The leaf-assignment
            # gather rides the row axis.
            per_ax_b, per_ax_o = {}, {}
            for axn, v in self._collective_per_axis.items():
                per_ax_b[axn] = int(v["bytes"] * hp)
                per_ax_o[axn] = int(v["ops"] * hp)
            if extra_b and per_ax_b:
                axn = self._dist.axis
                per_ax_b[axn] = per_ax_b.get(axn, 0) + extra_b
                per_ax_o[axn] = per_ax_o.get(axn, 0) + extra_o
            self._tele_superstep.update({
                "learner": self._dist.kind,
                "num_shards": int(self._dist.num_shards),
                "mesh_shape": [int(s) for s in
                               self._dist.mesh.devices.shape],
                "collective_bytes": int(
                    self._collective_per_pass * hp + extra_b),
                "collective_ops": int(
                    self._collective_ops_per_pass * hp + extra_o),
                "collective_bytes_axis": per_ax_b,
                "collective_ops_axis": per_ax_o,
            })
        return self._serve_fused()

    def _serve_fused(self) -> bool:
        """Append the next materialized tree of the in-flight block —
        one boosting iteration from the caller's point of view."""
        blk = self._fused_block
        t = blk["served"]
        blk["served"] = t + 1
        self._models.append(blk["trees"][t])
        self._tele_serving = True
        if blk["stop_idx"] is not None and t == blk["stop_idx"]:
            self._stop_flag = True
            Log.warning("Stopped training because there are no more "
                        "leaves that meet the split requirements")
            return True
        self.iter += 1
        return False

    def _fused_replay_score(self, pos: int):
        """(score, prev_score) after replaying ``pos`` block
        iterations from the stacked (leaf values, leaf assignment)
        pairs the scan returned — the same take_small + f32 add the
        scan performed, so the replayed score is bit-identical to the
        in-scan partial state.  The ONE implementation behind the
        stop path, the rewind/rollback restore and the mid-block
        ``train_score`` reader (they must never drift apart)."""
        import jax.numpy as jnp
        from ..ops.lookup import take_small
        blk = self._fused_block
        score, prev = blk["start_score"], None
        # row-sharded learners stitch the stacked leaf table at the
        # PADDED width (each shard emits its local block); the serial
        # scan stores it pre-sliced — normalize to the real row count
        n = score.shape[-1]
        for t in range(pos):
            prev = score
            score = score.at[0].add(
                take_small(blk["vals"][t],
                           blk["leaf_idx"][t][:n].astype(jnp.int32)))
        return score, prev

    def _fused_restore(self, pos: int) -> None:
        """Restore the exact sequential state at block-start + ``pos``
        iterations: partial score replay, host-RNG rewind with the
        block's consumed draws re-drawn, and the bagging-mask cache
        recomputed from its defining PRNG fold."""
        blk = self._fused_block
        self._score, self._prev_score = self._fused_replay_score(pos)
        self.iter = blk["start_iter"] + pos
        self._trees_dispatched = blk["start_tid"] + pos
        self._rng_feature.set_state(blk["rng_state"])
        for _ in range(pos):
            self._feature_fraction_mask()
        self._recompute_bag_cache()

    def _fused_rewind(self) -> None:
        """Discard the block's unserved trees (and every queued
        in-flight successor) and land on the served boundary — the
        escape hatch when eligibility drifts mid-block (a validation
        set attached, a custom-gradient call)."""
        self._discard_queue()
        blk = self._fused_block
        if blk is None:
            return
        self._fused_restore(blk["served"])
        self._fused_block = None

    def _fused_rollback(self) -> None:
        """Undo the last served iteration of the in-flight block."""
        self._discard_queue()
        blk = self._fused_block
        self._stop_flag = False
        self._invalidate_predictor()
        self._models.pop()
        served = blk["served"]
        stopped = blk["stop_idx"] is not None and \
            served > blk["stop_idx"]
        if stopped:
            # the stop serve never advanced ``iter``: score rolls to
            # after the last REAL iteration, the counter steps back
            # (mirroring the sequential rollback-after-stop behavior)
            self._fused_restore(served - 1)
            self.iter -= 1
        else:
            self._fused_restore(served - 1)
        self._fused_block = None

    # ---- elastic mesh recovery (parallel/elastic.py) -----------------
    def _elastic_beat(self, block: bool = False) -> None:
        """Beat the elastic heartbeat (dispatch start / block landed).
        The ``mesh.heartbeat:suppress`` fault drops beats — the
        injected stand-in for a shard that stops reporting progress
        without dying, driving the stall watchdog distinctly from a
        hung collective."""
        hb = getattr(self, "_elastic_heartbeat", None)
        if hb is None:
            return
        from ..utils import faults as _faults
        if _faults.fire("mesh.heartbeat") == "suppress":
            return
        hb.beat(block=block)

    def _abandoned_check(self, alive) -> None:
        """Raise out of an abandoned dispatch attempt BEFORE it
        commits state: once the elastic stall watchdog moved on, a
        re-mesh owns ``self`` and a late-returning zombie thread must
        not race its restored bookkeeping.  ``alive`` is THIS
        attempt's captured generation token — never the live
        attribute, which a retry overwrites with its own."""
        if alive is not None and not alive():
            from ..parallel.elastic import ElasticAbandoned
            raise ElasticAbandoned("fused dispatch abandoned by the "
                                   "elastic supervisor")

    def _mesh_collective_fault(self, mode: str, alive) -> None:
        """Consume one armed ``mesh.collective`` fault: ``error``
        raises the way XLA surfaces a dead peer, ``hang`` blocks the
        way a lost shard stalls the collective rendezvous (forever
        when unsupervised — faithful to the real failure), and
        ``sleep_<ms>`` delays the dispatch (drives the watchdog when
        heartbeats are suppressed)."""
        import time as _time
        from ..utils.faults import InjectedFault
        if mode == "error":
            raise InjectedFault(
                "injected collective failure (mesh.collective:error): "
                "simulated shard loss inside the fused block")
        if mode == "hang":
            while alive is None or alive():
                _time.sleep(0.02)
            from ..parallel.elastic import ElasticAbandoned
            raise ElasticAbandoned("hung collective abandoned by the "
                                   "elastic supervisor")
        if mode.startswith("sleep_"):
            _time.sleep(float(mode[len("sleep_"):]) / 1e3)

    def abort_inflight_dispatch(self) -> bool:
        """Restore the pre-block host state the in-flight fused
        dispatches consumed when they will never land (hung or failed
        collective): the feature-fraction RNG stream and the
        quantization-stream position are the only mutations between
        dispatch and fetch.  Under async pipelining MORE THAN ONE
        block can be outstanding; the live fence is the OLDEST
        dispatch's pre-state, so one restore rewinds across BOTH (all)
        blocks' RNG/quantization-stream consumption, and every queued
        block dies with it.  Returns True when a fence was armed."""
        # the abort fence extends to in-flight host->device STREAM
        # copies (io/stream.py BlockFetcher): a re-mesh rebuilding
        # construction must never race a stale upload window
        from ..io.stream import abort_active_fetchers
        abort_active_fetchers()
        fence = self.__dict__.pop("_dispatch_fence", None)
        self._sq = []
        if fence is None:
            return False
        self._rng_feature.set_state(fence["rng_state"])
        self._trees_dispatched = int(fence["tid"])
        return True

    def next_update_is_local(self) -> bool:
        """True when the next ``train_one_iter`` only serves an
        already-materialized tree from the in-flight fused block —
        pure host work, no device dispatch — so the elastic
        supervisor runs it inline instead of on a watched thread."""
        blk = self._fused_block
        return (blk is not None and blk["served"] < len(blk["trees"])
                and blk.get("lr") == self.shrinkage_rate and
                self._fused_ok())

    def stream_identity(self) -> Optional[Dict]:
        """The streamed-ingest cache identity this booster trains
        from, or None (in-memory dataset).  Checkpoint manifests
        record it so resume can verify the cache was REUSED instead
        of silently re-binned (docs/Streaming.md resume contract)."""
        info = getattr(self.train_set, "stream", None)
        if info is None:
            return None
        return {"cache_key": info.cache_key,
                "cache_dir": info.cache_dir,
                "chunk_rows": int(info.chunk_rows)}

    def pager_identity(self) -> Optional[Dict]:
        """The device-block pager geometry this booster trains under,
        or None (fully resident).  Checkpoint manifests record it so a
        resume knows the run was out-of-core; paged results are
        byte-identical to resident, so a geometry CHANGE on resume
        (different budget, different mesh) is legal — the record is
        provenance, not a constraint (docs/Streaming.md)."""
        if self._pager is None:
            return None
        ident = dict(self._pager.plan.identity())
        ident["mode"] = str(getattr(self.config, "paged_training",
                                    "auto")).lower()
        ident["hbm_budget_mb"] = float(
            getattr(self.config, "hbm_budget_mb", 0.0))
        return ident

    def mesh_identity(self) -> Dict:
        """The live mesh topology — recorded in checkpoint manifests
        (``ckpt/manager.py``) so resume can validate it against the
        restoring booster and re-shard across widths."""
        if self._dist is None:
            return {"learner": "serial", "num_shards": 1,
                    "mesh_shape": [1]}
        return {"learner": self._dist.kind,
                "num_shards": int(self._dist.num_shards),
                "mesh_shape": [int(s) for s in
                               self._dist.mesh.devices.shape]}

    def remesh(self, num_shards: Optional[int] = None, mesh=None,
               raw=None, snapshot: Optional[Dict] = None,
               mesh_shape=None) -> int:
        """Re-mesh entry point: rebuild the device mesh (narrower
        after shard loss, any explicit 1-D mesh, or — via
        ``mesh_shape=(R, F)`` — a 2-D data x feature mesh for the
        data2d learner) and continue BIT-exactly from the last served
        boundary.

        Lands on the served boundary first (dispatch-fence restore +
        the PR 3 exact rewind), captures the PR 5 bit-exact training
        snapshot, re-runs construction against the new mesh — every
        mesh-dependent decision (row/feature paddings, NamedShardings,
        tier gates, EFB when the survivor set collapses to serial) is
        re-derived exactly as a fresh booster would derive it — and
        restores the snapshot; the mesh-resident tensors land under
        the new ``DistributedBuilder.shardings()`` and the fused scan
        rebuilds lazily, keyed by the new mesh shape.
        ``num_shards=1`` falls back to the serial learner.  Returns
        the new shard count.

        ``snapshot``: a pre-captured :meth:`training_snapshot` to
        restore instead of capturing one here — the elastic
        supervisor's degrade-retry loop passes the snapshot it took
        BEFORE the first attempt, so a remesh that failed after its
        internal re-construction (leaving this booster blank) cannot
        make the retry restore blank state."""
        import jax
        self.abort_inflight_dispatch()
        if snapshot is None:
            self._fused_rewind()
            self._flush_pending()
            snapshot = self.training_snapshot()
        rec = getattr(self, "_telemetry", None)
        valid_sets = self.valid_sets
        cfg = self.config
        if mesh is None:
            if mesh_shape is not None:
                r, f = (int(s) for s in mesh_shape)
                num_shards = r * f
                if num_shards > 1:
                    from ..parallel.learners import make_mesh_2d
                    mesh = make_mesh_2d((r, f))
            if num_shards is None:
                raise ValueError("remesh needs num_shards, mesh_shape "
                                 "or an explicit mesh")
            if mesh is None:
                from ..parallel.learners import AXIS_NAME, make_mesh_for
                if int(num_shards) > 1:
                    mesh = make_mesh_for(int(num_shards))
                else:
                    # 1-device mesh: resolve_num_shards reads 1 and the
                    # construction falls back to the serial learner
                    mesh = jax.sharding.Mesh(
                        np.asarray(jax.devices()[:1]), (AXIS_NAME,))
        # the SAME recorder must survive the re-construction: blank
        # the file param so __init__ cannot open a second handle on
        # the same JSONL
        tf = cfg.telemetry_file
        cfg.telemetry_file = ""
        try:
            self.__init__(cfg, self.train_set, self.objective,
                          self.metrics, mesh=mesh)
        finally:
            cfg.telemetry_file = tf
        self.valid_sets = valid_sets
        if rec is not None and getattr(self, "_telemetry", None) is not rec:
            # re-adopt THIS booster's recorder even when __init__
            # already adopted the process-default one (telemetry_file
            # was blanked, so a live global recorder wins that race)
            # — the run's own stream must keep receiving records.
            # Re-adoption emits a fresh run_start, which resets
            # triage_run's superstep-warmup tracking: the post-re-mesh
            # recompile is per-shape warmup, not a storm
            self._telemetry = None
            self.attach_telemetry(rec)
        self.restore_training_snapshot(snapshot, raw=raw)
        return int(self._dist.num_shards) if self._dist is not None \
            else 1

    # ------------------------------------------------------------------
    def _dispatch_build(self, grad_k, hess_k, bag):
        """Pad + bag-weight one class's gradients, draw the feature
        mask and dispatch the jitted tree build.  Returns (device
        record dict, sample mask) — shared by the classic and
        pipelined iteration paths."""
        import jax
        import jax.numpy as jnp
        from ..utils.profiling import timed

        n, n_pad = self.num_data, self._n_pad
        with timed("tree/prep"):
            gp = jnp.pad(grad_k.astype(jnp.float32), (0, n_pad - n))
            hp = jnp.pad(hess_k.astype(jnp.float32), (0, n_pad - n))
            mask = self._base_mask
            if bag is not None:
                # weights scale grad/hess (GOSS/MVS upweighting); the
                # count channel stays presence-based like the
                # reference's subsets
                w = jnp.pad(jnp.asarray(bag, jnp.float32).reshape(-1),
                            (0, n_pad - n))
                gp = gp * w
                hp = hp * w
                mask = mask * (w > 0)
            fmask = self._feature_fraction_mask()
        kw = {}
        if self.grow_params.quantize:
            # fresh stochastic-rounding randomness per tree
            kw["quant_key"] = jax.random.fold_in(
                self._quant_key, self._trees_dispatched)
        self._trees_dispatched += 1
        with timed("tree/dispatch"):
            if self._bundle_maps is not None:
                rec = self._build_tree(
                    self._xt, gp, hp, mask, fmask, self._num_bins,
                    self._missing_type, self._is_cat, self.grow_params,
                    bundle_maps=self._bundle_maps, **kw)
            else:
                rec = self._build_tree(
                    self._xt, gp, hp, mask, fmask, self._num_bins,
                    self._missing_type, self._is_cat, self.grow_params,
                    **kw)
        return rec, mask

    def _materialize_pending(self) -> bool:
        """Fetch + host-materialize the in-flight tree; returns True
        when it could not split (the stop signal).

        The caller times this as ``tree/fetch`` — at steady state that
        time is overwhelmingly the WAIT for the in-flight build to
        finish on device, not transfer: the host dispatched tree t's
        build before fetching t-1's records, so the fetch blocks on
        t-1's remaining device compute while the ~one-RTT transfer and
        t's build overlap it.  Set LTPU_SPLIT_FETCH_TIMER=1 to split
        the phase into ``tree/device_wait`` (a 1-element sync) and the
        residual transfer (costs one extra tunnel round-trip per tree,
        so it is diagnosis-only)."""
        pending, self._pending = self._pending, None
        rec = pending["rec"]
        if os.environ.get("LTPU_SPLIT_FETCH_TIMER"):
            from ..utils.device import build_barrier
            from ..utils.profiling import timed
            with timed("tree/device_wait"):
                # build barrier: jax.block_until_ready where the
                # backend honors it; LTPU_SYNC_FETCH=1 falls back to
                # the 1-element fetch (remote-tunnel runtimes)
                build_barrier(rec["n_leaves"])
        recs = self._fetch_records(rec)
        if "n_arm_passes" in recs:
            self.last_arm_passes = int(recs["n_arm_passes"])
        n_leaves = int(recs["n_leaves"])
        if n_leaves <= 1:
            # non-finite gradients produce NaN gains everywhere and
            # masquerade as this legitimate stop (the unsplit tree's
            # returned record is all finite zeros, so the record
            # cannot tell the two apart).  Probe the gradients the
            # stop tree was dispatched with, plus the score — scalar
            # fetches on the at-most-once stop path only
            # (utils/health.py)
            import jax.numpy as jnp
            gh = pending.get("gh")
            ok = bool(jnp.all(jnp.isfinite(self._score)))
            if ok and gh is not None:
                ok = bool(jnp.all(jnp.isfinite(gh[0])) &
                          jnp.all(jnp.isfinite(gh[1])))
            if not ok:
                from ..utils.health import abort_nonfinite
                abort_nonfinite(getattr(self, "_telemetry", None),
                                max(self.iter - 1, 0), "pipelined",
                                "non-finite gradients/score at the "
                                "stop boundary")
            tree = Tree(2)
            tree.leaf_value[0] = pending["init_score"]
            if abs(pending["init_score"]) > _KEPS:
                self._score = self._score.at[0].add(
                    pending["init_score"])
            self._models.append(tree)
            return True
        tree = self._records_to_tree(recs)
        self._check_tree_health(tree, max(self.iter - 1, 0), "pipelined")
        tree.apply_shrinkage(pending["lr"])
        if abs(pending["init_score"]) > _KEPS:
            tree.add_bias(pending["init_score"])
        self._models.append(tree)
        return False

    def _flush_pending(self) -> None:
        if self._pending is not None:
            if self._materialize_pending():
                self._stop_flag = True

    # ---- numerical health (utils/health.py) --------------------------
    def _check_tree_health(self, tree, iteration: int,
                           phase: str) -> None:
        """Scan a just-materialized tree's leaf values (already
        host-side — zero extra device calls) for non-finite outputs;
        fail loudly instead of training on to a silent NaN model."""
        vals = tree.leaf_value[:max(tree.num_leaves, 1)]
        if not np.all(np.isfinite(vals)):
            from ..utils.health import abort_nonfinite
            n_bad = int((~np.isfinite(np.asarray(vals))).sum())
            abort_nonfinite(getattr(self, "_telemetry", None),
                            iteration, phase,
                            f"{n_bad} non-finite leaf value(s)")

    def _check_stop_health(self, grad, hess, iteration: int,
                           phase: str) -> None:
        """Non-finite gradients make every split gain NaN and
        masquerade as a legitimate "no splittable leaf" stop.  A stop
        happens at most once per training, so one scalar device fetch
        here costs nothing at steady state."""
        import jax.numpy as jnp
        ok = bool(jnp.all(jnp.isfinite(grad)) &
                  jnp.all(jnp.isfinite(hess)))
        if not ok:
            from ..utils.health import abort_nonfinite
            abort_nonfinite(getattr(self, "_telemetry", None),
                            iteration, phase,
                            "non-finite gradients at the stop "
                            "boundary (bad labels/scores, not an "
                            "exhausted tree)")

    def _train_one_iter_pipelined(self) -> bool:
        """Pipelined iteration: device work for tree t is dispatched
        (build + score update from the build's own final leaf values)
        BEFORE tree t-1's records are fetched, so the ~one-RTT fetch
        rides under device compute.  The materialized model trails the
        device state by one tree inside the loop; the ``models``
        property flushes, so every external reader sees the full list.
        Stop detection trails by one iteration (the stopping run gains
        one constant tree)."""
        import jax
        import jax.numpy as jnp
        from ..ops.lookup import take_small
        from ..utils.profiling import timed

        if self._stop_flag:
            return True
        self._prev_score = self._score
        self._prev_valid_scores = []
        init_score = 0.0
        if (self.iter == 0 and self.config.boost_from_average and
                not self._models and self._pending is None and
                self.train_set.metadata.init_score is None):
            init = self.objective.boost_from_score(0)
            if abs(init) > _KEPS:
                init_score = init
                self._score = self._score.at[0].add(init)
                Log.info("Start training from score %f", init)
        with timed("boosting/gradients"):
            # the jitted wrapper, not the eager chain: one fused pass,
            # and the same compiled math the fused super-step inlines
            # (bit-parity between the two paths requires it).  An
            # objective that opted out of the pure contract
            # (gradient_fn -> None) keeps its eager get_gradients.
            grad_fn = self.objective.gradient_fn() or \
                self.objective.get_gradients
            grad, hess = grad_fn(self._score)
        grad = jnp.atleast_2d(grad)
        hess = jnp.atleast_2d(hess)
        bag = self._bagging_mask(grad, hess)
        n = self.num_data
        rec, _ = self._dispatch_build(grad[0], hess[0], bag)
        with timed("tree/score_update"):
            vals = rec["leaf_values_final"] * \
                jnp.float32(self.shrinkage_rate)
            self._score = self._score.at[0].add(
                take_small(vals, rec["leaf_idx"][:n]))
        prev_stop = False
        if self._pending is not None:
            with timed("tree/fetch"):
                prev_stop = self._materialize_pending()
        self._pending = {"rec": rec, "init_score": init_score,
                         "lr": self.shrinkage_rate,
                         # kept for the stop-path health probe: a
                         # no-split stop must be distinguishable from
                         # NaN gradients killing every gain
                         "gh": (grad[0], hess[0])}
        self.iter += 1
        if prev_stop:
            self._check_stop_health(grad, hess, max(self.iter - 2, 0),
                                    "pipelined")
            self._stop_flag = True
            self._flush_pending()
            Log.warning("Stopped training because there are no more "
                        "leaves that meet the split requirements")
            return True
        return False

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration; returns True when training should stop
        (no splittable leaf).  With a telemetry recorder attached, every
        iteration emits a structured record (phase deltas, compile/
        retrace counters, tier, histogram passes, collective bytes)."""
        rec = getattr(self, "_telemetry", None)
        if rec is None:
            stop = self._train_one_iter_impl(grad, hess)
            if self._pager is not None:
                self._pager.raise_if_poisoned()
            # clear the superstep markers: a recorder attached later
            # must not mis-emit a stale block
            self.__dict__.pop("_tele_superstep", None)
            self.__dict__.pop("_tele_serving", None)
            return stop
        import time as _time
        from ..utils import profiling
        it = self.iter
        ph0 = profiling.snapshot()
        t0 = _time.perf_counter()
        stop = self._train_one_iter_impl(grad, hess)
        if self._pager is not None:
            self._pager.raise_if_poisoned()
        dur_ms = (_time.perf_counter() - t0) * 1e3
        ss = self.__dict__.pop("_tele_superstep", None)
        if ss is not None:
            # fused super-step: ONE record per K iterations carrying
            # the block's amortized phase deltas and compile counters;
            # the K-1 serve calls that follow emit nothing (their cost
            # is microseconds of host list work)
            self._tele_serving = False
            cdelta, self._tele_counters_last = rec.counters_delta(
                self._tele_counters_last)
            fields = {
                "iter": it,
                "k": int(ss["k"]),
                "duration_ms": round(dur_ms, 3),
                "phases_ms": profiling.delta_ms(ph0),
                "counters": cdelta,
                "tier": self.tier_decision["tier"],
                "trees_per_iter": self.num_tree_per_iteration,
                "n_trees": len(self._models),
                "stopped": bool(stop),
            }
            if ss.get("hist_passes") is not None:
                fields["hist_passes"] = int(ss["hist_passes"])
            # async pipelining observability: the configured in-flight
            # depth and the wall this block's device compute ran
            # overlapped with host work (dispatch -> fetch window).
            # triage_run.py flags depth > 0 with ~zero overlap as
            # "pipelining silently disabled"
            fields["pipeline_depth"] = int(ss.get("pipeline_depth", 0))
            fields["fetch_overlap_s"] = float(
                ss.get("fetch_overlap_s", 0.0))
            # best-split engine per block: which scan ran and, when it
            # fell back to XLA, the gate that rejected the Pallas tier
            # (triage_run.py flags xla-on-a-TPU-backend as MED)
            fields["split_kernel"] = self.tier_decision.get(
                "split_kernel", "xla")
            sf = self.tier_decision.get("gates", {}).get("split")
            if sf:
                fields["split_fallback"] = sf
            # sharded super-step: per-block collective accounting +
            # mesh identity (the weak-scaling triage reads these —
            # per-iteration time growing with num_shards at constant
            # collective bytes is the dispatch-overhead signature the
            # single-program refactor exists to kill)
            for key in ("learner", "num_shards", "mesh_shape",
                        "collective_bytes", "collective_ops",
                        "collective_bytes_axis", "collective_ops_axis"):
                if key in ss:
                    fields[key] = ss[key]
            rec.emit("superstep", **fields)
            self._emit_pager_flush(rec, it)
            return stop
        if self.__dict__.pop("_tele_serving", False):
            # serving a tree from an already-recorded super-step block
            return stop
        cdelta, self._tele_counters_last = rec.counters_delta(
            self._tele_counters_last)
        fields = {
            "iter": it,
            "duration_ms": round(dur_ms, 3),
            "phases_ms": profiling.delta_ms(ph0),
            "counters": cdelta,
            "tier": self.tier_decision["tier"],
            "trees_per_iter": self.num_tree_per_iteration,
            # raw list length: the models property would flush the
            # pipelined in-flight tree and kill the fetch overlap
            "n_trees": len(self._models) +
            (1 if self._pending is not None else 0),
            "stopped": bool(stop),
        }
        passes = getattr(self, "last_arm_passes", None)
        if passes is not None:
            hp = (int(passes) + 1) * self.num_tree_per_iteration
            fields["hist_passes"] = hp
            # pool hit rate: fraction of the 2S child histograms a tree
            # needed that came from the pool (subtraction trick / armed
            # cache) instead of a fresh device pass.  Uses the last
            # MATERIALIZED tree's split count (the pipelined path trails
            # by one tree; the rate is a per-booster steady-state stat)
            if self._models and self._models[-1].num_leaves > 1:
                S = self._models[-1].num_leaves - 1
                fields["pool_hit_rate"] = round(
                    max(0.0, 1.0 - hp / float(2 * S)), 4)
        if self._collective_per_pass:
            # passes this iteration: measured for speculative/wave
            # builds; otherwise ~one fresh smaller-child pass per
            # split plus the root (subtraction covers the sibling)
            hp = fields.get("hist_passes")
            if hp is None:
                n_leaves = (self._models[-1].num_leaves if self._models
                            else self.config.num_leaves)
                hp = max(n_leaves, 1) * self.num_tree_per_iteration
            fields["collective_bytes"] = int(
                self._collective_per_pass * hp)
            fields["collective_ops"] = int(
                self._collective_ops_per_pass * hp)
            if self._dist is not None:
                fields["learner"] = self._dist.kind
                fields["num_shards"] = int(self._dist.num_shards)
                fields["mesh_shape"] = [
                    int(s) for s in self._dist.mesh.devices.shape]
                if self._collective_per_axis:
                    fields["collective_bytes_axis"] = {
                        k: int(v["bytes"] * hp)
                        for k, v in self._collective_per_axis.items()}
        rec.emit("iteration", **fields)
        self._emit_pager_flush(rec, it)
        return stop

    def _emit_pager_flush(self, rec, it: int) -> None:
        """One pager record per telemetry-visible training step: the
        DELTA of the PageStore's cumulative stats since the last
        flush (pages/bytes/overlap_s/stalls — the series the
        pager_no_overlap rule reads)."""
        if self._pager is None or rec is None:
            return
        delta = self._pager.stats_delta(self._pager_last or {})
        self._pager_last = self._pager.stats()
        if delta.get("pages", 0) or delta.get("columns", 0):
            rec.emit("pager", event="flush", iter=int(it), **delta)

    def _train_one_iter_impl(self, grad: Optional[np.ndarray] = None,
                             hess: Optional[np.ndarray] = None) -> bool:
        import jax.numpy as jnp

        fused = grad is None and self._fused_ok()
        blk = self._fused_block
        if blk is not None:
            in_flight = blk["served"] < len(blk["trees"])
            # a learning_rates schedule changed the shrinkage since
            # dispatch: the unserved trees were built with the old
            # rate — rewind and redispatch at the new one
            lr_drift = blk.get("lr") != self.shrinkage_rate
            if fused and in_flight and not lr_drift:
                return self._serve_fused()
            if in_flight:
                # eligibility drifted mid-block (custom gradients, a
                # freshly attached valid set, a shrinkage change):
                # rewind to the served boundary, then fall through
                self._fused_rewind()
            elif not fused:
                self._fused_block = None  # rollback window closed
                if self._sq:
                    # fused mode just disengaged with blocks still in
                    # flight: drain them (restoring their consumed RNG
                    # draws) and rebuild the bagging cache the drained
                    # chain may have donated away
                    self._discard_queue()
                    self._recompute_bag_cache()
        if fused and not self._fused_bias_pending():
            return self._train_superstep()
        if grad is None and self._pipeline_ok():
            return self._train_one_iter_pipelined()
        self._flush_pending()
        if self._stop_flag:
            return True
        self._prev_score = self._score  # snapshot for rollback (immutable)
        # valid scores are NOT snapshotted per iteration: rollback
        # restores them by subtracting the popped trees' predictions
        # (``GBDT::RollbackOneIter`` does the same via Shrinkage(-1) +
        # AddScore) — a full f64 copy per valid set per iteration was
        # dead weight on the hot loop whenever nobody rolls back
        init_scores = [0.0] * self.num_tree_per_iteration
        custom = grad is not None
        if not custom:
            if (self.iter == 0 and self.config.boost_from_average and
                    not self.models and
                    self.train_set.metadata.init_score is None and
                    self.objective is not None and
                    self.num_features > 0):
                for k in range(self.num_tree_per_iteration):
                    init = self.objective.boost_from_score(k)
                    if abs(init) > _KEPS:
                        init_scores[k] = init
                        self._score = self._score.at[k].add(init)
                        for vs in self.valid_sets:
                            vs.score[k] += init
                        Log.info("Start training from score %f", init)
            from ..utils.profiling import timed
            with timed("boosting/gradients"):
                grad_fn = self.objective.gradient_fn() or \
                    self.objective.get_gradients
                grad, hess = grad_fn(self._score)
            grad = jnp.atleast_2d(grad)
            hess = jnp.atleast_2d(hess)
        else:
            grad = jnp.asarray(np.atleast_2d(np.asarray(grad, np.float32)))
            hess = jnp.asarray(np.atleast_2d(np.asarray(hess, np.float32)))

        from ..utils.profiling import timed
        bag = self._bagging_mask(grad, hess)
        should_stop = True
        for k in range(self.num_tree_per_iteration):
            with timed("tree/build"):
                tree = self._train_one_tree(grad[k], hess[k], bag,
                                            init_scores[k])
            if tree.num_leaves > 1:
                should_stop = False
            self.models.append(tree)
        if should_stop:
            self._check_stop_health(grad, hess, self.iter, "tree")
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            return True
        self.iter += 1
        return False

    def _train_one_tree(self, grad, hess, bag, init_score: float) -> Tree:
        import jax.numpy as jnp
        from ..utils.profiling import timed

        n = self.num_data
        recs = None
        if self.num_features == 0:
            rec = None
            n_leaves = 1
            mask = self._base_mask
        else:
            rec, mask = self._dispatch_build(grad, hess, bag)
            with timed("tree/fetch"):
                # one packed device->host transfer per tree; doubles as
                # the device sync (tunnel round-trips cost ~120ms, so a
                # separate 1-element sync fetch would double the toll)
                recs = self._fetch_records(rec)
            n_leaves = int(recs["n_leaves"])
            if "n_arm_passes" in recs:
                self.last_arm_passes = int(recs["n_arm_passes"])

        if n_leaves <= 1:
            # constant tree holding the init score (gbdt.cpp:380-397)
            tree = Tree(2)
            out = init_score
            tree.leaf_value[0] = out
            if abs(out) > _KEPS:
                tree_idx = len(self.models) % self.num_tree_per_iteration
                self._score = self._score.at[tree_idx].add(out)
                for vs in self.valid_sets:
                    vs.score[tree_idx] += out
            if self._track_train_leaf:
                self._train_leaf_idx.append(None)
                for vs in self.valid_sets:
                    vs.leaf_idx_per_tree.append(None)
            return tree

        with timed("tree/to_tree"):
            tree = self._records_to_tree(recs)
        self._check_tree_health(tree, self.iter, "tree")
        if self._track_train_leaf:
            # compact dtype ON DEVICE: leaf ids fit uint8/16 and the
            # device->host link is slow, so never ship int32
            dt = jnp.uint8 if self.config.num_leaves <= 256 else jnp.uint16
            self._train_leaf_idx.append(
                np.asarray(rec["leaf_idx"][:n].astype(dt)))
        # leaf renewal hook (RenewTreeOutput) — objective-specific
        if self.objective is not None:
            with timed("tree/renew"):
                self.objective.renew_tree_output(
                    tree, self._score, rec["leaf_idx"][:n], mask)
        tree.apply_shrinkage(self.shrinkage_rate)
        with timed("tree/score_update"):
            # train-score update via the leaf assignment from the build;
            # the (N,) table lookup runs as the select-chain kernel (an
            # XLA gather here costs ~150 ms per iteration at bench
            # shape — ops/lookup.py)
            from ..ops.lookup import take_small
            vals = jnp.asarray(tree.leaf_value[:self.config.num_leaves],
                               jnp.float32)
            vals = jnp.pad(
                vals, (0, max(0, self.config.num_leaves - vals.shape[0])))
            tree_idx = len(self.models) % self.num_tree_per_iteration
            self._score = self._score.at[tree_idx].add(
                take_small(vals, rec["leaf_idx"][:n]))
        # valid scores: device split-record replay when the binned
        # matrix is resident, host traversal fallback otherwise
        from ..ops.grow import route_rows
        dt_leaf = np.uint8 if self.config.num_leaves <= 256 else np.uint16
        with timed("tree/valid"):
            for vs in self.valid_sets:
                if vs.xt is not None:
                    li = route_rows(vs.xt, rec["leaf"], rec["feature"],
                                    rec["left_mask"], rec["valid"],
                                    self.config.num_leaves,
                                    bundle_maps=self._bundle_maps)
                    if self._track_train_leaf:
                        # DART drops/renormalizations replay per-tree
                        # valid contributions from this table instead
                        # of host tree traversals
                        la = np.asarray(li.astype(dt_leaf))
                        vs.leaf_idx_per_tree.append(la)
                        vs.score[tree_idx] += tree.leaf_value[
                            la.astype(np.int32)]
                    else:
                        vs.score[tree_idx] += np.asarray(
                            take_small(vals, li), np.float64)
                else:
                    if self._track_train_leaf:
                        la = tree.predict_leaf_index(vs.raw).astype(
                            dt_leaf)
                        vs.leaf_idx_per_tree.append(la)
                        vs.score[tree_idx] += tree.leaf_value[
                            la.astype(np.int32)]
                    else:
                        vs.score[tree_idx] += tree.predict(vs.raw)
        if abs(init_score) > _KEPS:
            tree.add_bias(init_score)
        return tree

    # ------------------------------------------------------------------
    def _fetch_records(self, rec):
        """ONE device->host transfer per tree: every split record except
        the (N,) leaf assignment (which stays on device for the score
        update), concatenated into a single f32 buffer on device —
        ``device_get`` on a dict pays one ~10ms tunnel round-trip PER
        array, and the records hold ~15.  All record values (leaf ids,
        bins, gains, stats, flag bits) are exactly representable in f32.
        """
        import jax
        import jax.numpy as jnp

        keys = [k for k in sorted(rec) if k != "leaf_idx"]
        layout = [(k, tuple(rec[k].shape), np.dtype(rec[k].dtype))
                  for k in keys]
        if self._rec_layout != layout:
            # keyed on SHAPES too: the fused super-step fetches stacked
            # (K, ...) records through the same pack, and the tail
            # block's K differs
            self._rec_layout = layout
            self._rec_pack = jax.jit(lambda r: jnp.concatenate(
                [r[k].astype(jnp.float32).reshape(-1) for k in keys]))
        flat = np.asarray(self._rec_pack({k: rec[k] for k in keys}))
        out, off = {}, 0
        for k, shp, dt in self._rec_layout:
            size = int(np.prod(shp)) if shp else 1
            out[k] = flat[off:off + size].reshape(shp).astype(dt)
            off += size
        return out

    # ------------------------------------------------------------------
    def _records_to_tree(self, rec) -> Tree:
        return records_to_tree(rec, self.config, self.train_set,
                               counts_proxy=getattr(self, "_counts_proxy",
                                                    False))

    # ---- checkpoint/resume (lightgbm_tpu/ckpt/) ----------------------
    def completed_iterations(self) -> int:
        """Iterations fully materialized on the host — mid-fused-block
        this is the SERVED boundary, not the block-end state the
        device score holds."""
        blk = getattr(self, "_fused_block", None)
        if blk is not None and blk["served"] < len(blk["trees"]):
            return blk["start_iter"] + blk["served"]
        return self.iter

    def training_snapshot(self) -> Dict:
        """Model-consistent training state at the last COMPLETED
        iteration, as host arrays — the capture side of the checkpoint
        subsystem.  Mid-fused-block, the state is aligned to the
        served boundary exactly the way :meth:`_fused_restore` would
        land there (partial score replay, host-RNG re-advance), but
        WITHOUT disturbing the in-flight block: training continues
        serving from it after the save."""
        blk = getattr(self, "_fused_block", None)
        if blk is not None and blk["served"] < len(blk["trees"]):
            served = blk["served"]
            score, _ = self._fused_replay_score(served)
            it = blk["start_iter"] + served
            tid = blk["start_tid"] + served
            cur = self._rng_feature.get_state()
            self._rng_feature.set_state(blk["rng_state"])
            for _ in range(served):
                self._feature_fraction_mask()
            rng_state = self._rng_feature.get_state()
            self._rng_feature.set_state(cur)
        else:
            _ = self.models            # flush any pipelined tree
            score = self._score
            it = self.iter
            if self._sq:
                # block boundary with successor blocks dispatched but
                # unfetched: the LIVE stream positions include their
                # consumed feature-fraction draws and quantization
                # tids — model-consistent state is the OLDEST queued
                # dispatch's pre-state (exactly the fence an abort
                # would restore; the resumed run redispatches those
                # blocks itself)
                tid = int(self._sq[0]["fence"]["tid"])
                rng_state = self._sq[0]["fence"]["rng_state"]
            else:
                tid = self._trees_dispatched
                rng_state = self._rng_feature.get_state()
        return {
            "iter": int(it),
            "trees_dispatched": int(tid),
            "shrinkage_rate": float(self.shrinkage_rate),
            "stopped": bool(self._stop_flag),
            "score": np.asarray(score),
            "rng_feature": rng_state,
            "models": list(self._models),
            "valid_scores": {vs.name: np.asarray(vs.score)
                             for vs in self.valid_sets},
            "extra": self._extra_ckpt_state(),
        }

    def _extra_ckpt_state(self) -> Dict:
        """Subclass hook: boosting-mode state beyond the base carry
        (DART's drop RNG/weights, models/boosting.py)."""
        return {}

    def _restore_extra_ckpt_state(self, extra: Dict, raw) -> None:
        pass

    def restore_training_snapshot(self, snap: Dict, raw=None) -> None:
        """Install a :meth:`training_snapshot` into this (freshly
        constructed) booster so the next ``train_one_iter`` continues
        bit-identically to the run the snapshot was taken from: exact
        device score carry, host-RNG stream position, quantization
        stream position, and the bagging-cycle cache recomputed from
        its defining PRNG fold.  Valid sets must already be
        registered; their accumulated scores (path-dependent under
        DART renormalization) are overwritten from the snapshot."""
        import jax.numpy as jnp
        self._fused_block = None
        self._sq = []
        self.__dict__.pop("_dispatch_fence", None)
        self._pending = None
        self._stop_flag = bool(snap.get("stopped", False))
        self.models = list(snap["models"])   # setter bumps the predictor
        self.iter = int(snap["iter"])
        self._trees_dispatched = int(snap["trees_dispatched"])
        self.shrinkage_rate = float(snap["shrinkage_rate"])
        self._score = jnp.asarray(np.asarray(snap["score"], np.float32))
        if self._dist is not None:
            # mesh-resident contract: the restored carry goes back on
            # the mesh replicated, exactly as construction placed the
            # fresh one — a host-placed carry would compile a second
            # executable for its input sharding on the first block
            import jax
            self._score = jax.device_put(self._score,
                                         self._dist.shardings()["rep"])
        self._prev_score = None
        self._prev_valid_scores = []
        self._rng_feature.set_state(snap["rng_feature"])
        cfg = self.config
        if (self._bagging_active() and self.iter > 0 and
                type(self)._bagging_mask is GBDT._bagging_mask):
            # the bernoulli/stratified cache is a pure function of the
            # last bagging_freq boundary (same recompute as
            # _fused_restore); GOSS/MVS masks are functions of the
            # iteration's gradients and need no cache
            last_draw = (self.iter - 1) // cfg.bagging_freq * \
                cfg.bagging_freq
            self._cached_bag = self._draw_bag_mask(last_draw)
        vsc = snap.get("valid_scores") or {}
        k = max(self.num_tree_per_iteration, 1)
        for vs in self.valid_sets:
            if vs.name in vsc:
                arr = np.asarray(vsc[vs.name], np.float64)
                if arr.size != vs.score.size:
                    Log.fatal("checkpointed valid set %r has %d scores, "
                              "the registered one needs %d — resume "
                              "requires the same validation data",
                              vs.name, arr.size, vs.score.size)
                vs.score = arr.reshape(vs.score.shape)
            else:
                # registered at resume but absent from the checkpoint:
                # add_valid replayed ZERO trees (it ran before this
                # restore installed them), so replay the model now —
                # the same continue-training semantics add_valid gives
                # an init_model (scores from this point on accumulate
                # incrementally like any fresh registration)
                Log.warning("valid set %r was not registered when the "
                            "checkpoint was taken; replaying the "
                            "restored model into its score", vs.name)
                for i, tree in enumerate(self._models):
                    vs.score[i % k] += tree.predict(vs.raw)
        if self._track_train_leaf:
            # per-tree leaf assignments are discrete and recomputable
            # exactly from the restored trees (init_from_model does
            # the same); constant trees keep their None sentinel
            dt = np.uint8 if cfg.num_leaves <= 256 else np.uint16
            if raw is not None:
                self._train_leaf_idx = [
                    None if t.num_leaves <= 1 else
                    t.predict_leaf_index(raw).astype(dt)
                    for t in self._models]
            else:
                # streamed dataset: replay chunk-by-chunk off the raw
                # source (docs/Streaming.md), like init_from_model
                src = getattr(self.train_set, "raw_source", None)
                sinfo = getattr(self.train_set, "stream", None)
                if src is None or sinfo is None:
                    Log.fatal("resuming %s requires the training "
                              "set's raw matrix (free_raw_data="
                              "False)", type(self).__name__)
                from ..io.cache import chunk_grid
                parts: List[List[np.ndarray]] = \
                    [[] for _ in self._models]
                for start, stop in chunk_grid(self.num_data,
                                              sinfo.chunk_rows):
                    blk = src.read_rows(start, stop)
                    for i, t in enumerate(self._models):
                        if t.num_leaves > 1:
                            parts[i].append(
                                t.predict_leaf_index(blk).astype(dt))
                self._train_leaf_idx = [
                    None if t.num_leaves <= 1 else
                    np.concatenate(parts[i])
                    for i, t in enumerate(self._models)]
            for vs in self.valid_sets:
                vs.leaf_idx_per_tree = [
                    None if t.num_leaves <= 1 else
                    t.predict_leaf_index(vs.raw).astype(dt)
                    for t in self._models]
        self._restore_extra_ckpt_state(dict(snap.get("extra") or {}),
                                       raw)

    # ------------------------------------------------------------------
    @property
    def train_score(self) -> np.ndarray:
        blk = getattr(self, "_fused_block", None)
        if blk is not None and blk["served"] < len(blk["trees"]):
            # mid-block the device score is ahead of the model (it
            # holds the end-of-block state); replay the served prefix
            # non-destructively so readers see the model-consistent
            # score — fusion eligibility already excludes every
            # per-iteration reader (metrics, custom fobj)
            score, _ = self._fused_replay_score(blk["served"])
            return np.asarray(score)[:, :self.num_data]
        return np.asarray(self._score)[:, :self.num_data]

    def _eval_one_set(self, name: str, score_kn: np.ndarray,
                      meta: Metadata) -> List[Tuple[str, str, float, bool]]:
        """Run every metric on one dataset.  ``score_kn`` is the raw
        (num_tree_per_iteration, rows) score block; multiclass metrics
        receive the full (rows, K) matrix, single-output objectives the
        1-D vector.  Rank metrics report one entry per eval_at position
        (the reference's ndcg@1..ndcg@5 rows)."""
        if self.num_tree_per_iteration > 1:
            score = np.asarray(score_kn, np.float64).T  # (rows, K)
        else:
            score = np.asarray(score_kn[0], np.float64)
        if self.objective is not None:
            score = self.objective.convert_output(score)
        label = np.asarray(meta.label, np.float64)
        out = []
        for m in self.metrics:
            if hasattr(m, "eval_all"):
                for mname, val in m.eval_all(label, score, meta.weight,
                                             meta.query_boundaries):
                    out.append((name, mname, val, m.higher_better))
            else:
                out.append((name, m.name,
                            m.eval(label, score, meta.weight,
                                   meta.query_boundaries), m.higher_better))
        return out

    def eval_set(self) -> List[Tuple[str, str, float, bool]]:
        """Evaluate all metrics on train (optional) + valid sets.
        Returns (dataset_name, metric_name, value, higher_better)."""
        out = []
        if self.config.is_provide_training_metric and self.objective:
            out.extend(self._eval_one_set("training", self.train_score,
                                          self.train_set.metadata))
        for vs in self.valid_sets:
            out.extend(self._eval_one_set(vs.name, vs.score, vs.metadata))
        return out

    # ------------------------------------------------------------------
    def _use_predict_engine(self, override=None) -> bool:
        from ..ops.predict import engine_enabled
        if not engine_enabled():
            return False
        if override is not None:
            return bool(override)
        return bool(getattr(self.config, "predict_engine", True))

    def _engine(self):
        """The process-wide engine, with this booster's LRU capacity
        preference applied (``predict_cache_slots``; last booster to
        predict wins — the cache is shared by design)."""
        from ..ops.predict import get_engine
        eng = get_engine()
        slots = int(getattr(self.config, "predict_cache_slots", 0) or 0)
        if slots > 0 and slots != eng.cache_size:
            eng.set_cache_size(slots)
        return eng

    def _flat_forest(self):
        """Flattened SoA forest tables (ops/predict.py), cached until
        the model mutates — appends/pops change the tree count in the
        key, in-place tree mutations bump ``_model_version`` via
        :meth:`_invalidate_predictor`.

        Same-process train->predict takes the DEVICE-HANDOFF path
        (``predict_device_handoff``, default on): per-tree flat rows
        are extracted once as trees materialize from the training
        fetch and only the delta since the last handoff is walked —
        zero full-forest host repacks at the train->serve seam
        (``flatten_full_repacks`` telemetry counter stays 0;
        byte-identical to :func:`~..ops.predict.flatten_forest`,
        pinned by tests/test_pipeline.py).  Cold loads (model file,
        handoff disabled) keep the numpy full-repack path."""
        from ..ops.predict import flatten_forest, flatten_forest_device
        models = self.models            # flushes any pending tree
        key = (self._model_version, len(models))
        if self._flat_cache is None or self._flat_cache[0] != key:
            if (bool(getattr(self.config, "predict_device_handoff",
                             True)) and self.train_set is not None):
                flat = flatten_forest_device(
                    models, self.num_tree_per_iteration,
                    self._tree_flats)
            else:
                flat = flatten_forest(models,
                                      self.num_tree_per_iteration)
            self._flat_cache = (key, flat)
        return self._flat_cache[1]

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1,
                    early_stop: bool = False, early_stop_freq: int = 10,
                    early_stop_margin: float = 10.0,
                    predict_engine=None,
                    predict_chunk_rows=None) -> np.ndarray:
        """Raw scores (rows,) or (rows, num_class).

        Served by the flattened jitted engine (``ops/predict.py``);
        ``LTPU_PREDICT_ENGINE=0`` or ``predict_engine=false`` falls
        back to the per-tree host loop (the oracle path).  The
        ``predict_engine``/``predict_chunk_rows`` arguments are
        per-call overrides of the config values (the C-API passes them
        from the parameters string without mutating shared state).

        ``early_stop``: per-row prediction early stopping
        (``prediction_early_stop.cpp``): every ``early_stop_freq``
        iterations, rows whose margin (|score| for binary, top1-top2
        for multiclass) exceeds ``early_stop_margin`` stop accumulating
        further trees."""
        import time as _time
        t0 = _time.perf_counter()
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        k = self.num_tree_per_iteration
        n_trees = len(self.models)
        if num_iteration is not None and num_iteration > 0:
            n_trees = min(n_trees, num_iteration * k)
        use_es = early_stop and k >= 1 and not self.average_output
        used_engine = n_trees > 0 and X.shape[0] > 0 and \
            self._use_predict_engine(predict_engine)
        if used_engine:
            out = self._engine().predict_raw(
                self._flat_forest(), X, n_trees, early_stop=use_es,
                early_stop_freq=early_stop_freq,
                early_stop_margin=early_stop_margin,
                chunk_rows=predict_chunk_rows or
                getattr(self.config, "predict_chunk_rows", 0))
        else:
            out = self._predict_raw_loop(X, n_trees, k, use_es,
                                         early_stop_freq,
                                         early_stop_margin)
        if self.average_output and n_trees:
            out = out / max(n_trees // k, 1)
        self._record_predict("raw", X.shape[0], n_trees, used_engine, t0)
        return out[0] if k == 1 else out.T

    def _predict_raw_loop(self, X: np.ndarray, n_trees: int, k: int,
                          use_es: bool, early_stop_freq: int,
                          early_stop_margin: float) -> np.ndarray:
        """Per-tree host traversal — the engine's bit-level oracle."""
        n = X.shape[0]
        out = np.zeros((k, n), dtype=np.float64)
        active = np.ones(n, dtype=bool)
        for i in range(n_trees):
            if use_es and not np.all(active):
                idx = np.nonzero(active)[0]
                if len(idx) == 0:
                    break
                out[i % k, idx] += self.models[i].predict(X[idx])
            else:
                out[i % k] += self.models[i].predict(X)
            if use_es and (i + 1) % (early_stop_freq * k) == 0:
                if k == 1:
                    # binary margin = 2|raw| (prediction_early_stop.cpp)
                    margin = 2.0 * np.abs(out[0])
                else:
                    top2 = np.partition(out, k - 2, axis=0)[-2:]
                    margin = top2[1] - top2[0]
                active &= margin < early_stop_margin
        return out

    def predict(self, X: np.ndarray, num_iteration: int = -1,
                **engine_kw) -> np.ndarray:
        raw = self.predict_raw(X, num_iteration, **engine_kw)
        if self.objective is not None:
            return self.objective.convert_output(raw)
        return raw

    def _shap_forest(self):
        """Flattened SHAP path-descriptor tables (ops/shap.py), cached
        until the model mutates — same invalidation rules as
        :meth:`_flat_forest`."""
        from ..ops.shap import flatten_forest_shap
        models = self.models            # flushes any pending tree
        key = (self._model_version, len(models))
        cache = getattr(self, "_shap_cache", None)
        if cache is None or cache[0] != key:
            cache = (key, flatten_forest_shap(
                models, self.num_tree_per_iteration))
            self._shap_cache = cache
        return cache[1]

    def predict_contrib(self, X: np.ndarray, num_iteration: int = -1,
                        predict_engine=None,
                        predict_chunk_rows=None) -> np.ndarray:
        """Per-row SHAP contributions (``PredictContrib`` layout:
        (rows, nf+1), multiclass flattened to (rows, k*(nf+1)) with
        per-class bias columns).  Served by the flattened explanation
        engine (``ops/shap.py``); the per-tree host recursion stays
        the oracle path behind the same ``predict_engine`` /
        ``LTPU_PREDICT_ENGINE`` gates as :meth:`predict_raw`."""
        import time as _time
        t0 = _time.perf_counter()
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        k = self.num_tree_per_iteration
        n_trees = len(self.models)
        if num_iteration is not None and num_iteration > 0:
            n_trees = min(n_trees, num_iteration * k)
        rows, nf = X.shape
        used_engine = n_trees > 0 and rows > 0 and \
            self._use_predict_engine(predict_engine)
        if used_engine:
            from ..ops.shap import get_shap_engine
            sf = self._shap_forest()
            raw = get_shap_engine().predict_contrib(
                sf, X, n_trees,
                chunk_rows=predict_chunk_rows or
                getattr(self.config, "predict_chunk_rows", 0))
            F = sf.num_features
            out = np.zeros((rows, k, nf + 1), dtype=np.float64)
            c = min(F, nf)
            out[:, :, :c] = np.moveaxis(raw[:, :c, :], 2, 0)
            out[:, :, -1] = raw[:, F, :].T
            out = out[:, 0, :] if k == 1 else \
                out.reshape(rows, k * (nf + 1))
        else:
            from ..ops.shap import predict_contrib as _host_contrib
            out = _host_contrib(self.models, X, num_iteration, k)
        self._record_predict("contrib", rows, n_trees, used_engine, t0)
        return out

    def predict_leaf_index(self, X: np.ndarray, num_iteration: int = -1,
                           predict_engine=None,
                           predict_chunk_rows=None) -> np.ndarray:
        import time as _time
        t0 = _time.perf_counter()
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        n_trees = len(self.models)
        if num_iteration is not None and num_iteration > 0:
            n_trees = min(n_trees, num_iteration * self.num_tree_per_iteration)
        used_engine = n_trees > 0 and X.shape[0] > 0 and \
            self._use_predict_engine(predict_engine)
        if used_engine:
            out = self._engine().predict_leaf_index(
                self._flat_forest(), X, n_trees,
                chunk_rows=predict_chunk_rows or
                getattr(self.config, "predict_chunk_rows", 0))
        else:
            out = np.stack([self.models[i].predict_leaf_index(X)
                            for i in range(n_trees)], axis=1)
        self._record_predict("leaf", X.shape[0], n_trees, used_engine, t0)
        return out

    def _record_predict(self, kind: str, rows: int, n_trees: int,
                        used_engine: bool, t0: float) -> None:
        """One ``predict`` telemetry record per call.  Cache counters
        are reported CUMULATIVE from the process-wide engine — the
        merge-safe form under concurrent predicts (utils/telemetry.py
        aggregates by keeping the latest value)."""
        rec = getattr(self, "_telemetry", None)
        if rec is None:
            return
        import time as _time
        fields = {"kind": kind, "rows": int(rows), "n_trees": int(n_trees),
                  "engine": bool(used_engine),
                  "duration_ms": round((_time.perf_counter() - t0) * 1e3,
                                       3)}
        try:
            if kind == "contrib":
                from ..ops.shap import get_shap_engine
                fields["cache"] = get_shap_engine().cache_info()
            else:
                from ..ops.predict import get_engine
                fields["cache"] = get_engine().cache_info()
        except Exception:
            pass
        rec.emit("predict", **fields)

    def init_from_model(self, models: List[Tree],
                        raw: Optional[np.ndarray]) -> None:
        """Continue-training: seed this booster with an existing model's
        trees (``engine.py`` init_model / ``application.cpp:90-93``) and
        replay them into the training score.  ``raw`` is the training
        set's raw feature matrix (the init model may have been trained
        with different bin boundaries, so replay must use real values).
        """
        import jax.numpy as jnp
        if len(models) % max(self.num_tree_per_iteration, 1):
            Log.fatal("init model has %d trees, not a multiple of "
                      "num_tree_per_iteration=%d", len(models),
                      self.num_tree_per_iteration)
        import copy
        # deep-copy: later in-place mutations (DART renormalization,
        # refit) must not corrupt the donor booster's trees
        self.models = [copy.deepcopy(t) for t in models]
        self.iter = len(models) // max(self.num_tree_per_iteration, 1)
        self._trees_dispatched = len(models)
        k = self.num_tree_per_iteration
        dt = np.uint8 if self.config.num_leaves <= 256 else np.uint16
        add = np.zeros((k, self.num_data), np.float32)
        leaf_idx: List[Optional[np.ndarray]] = []
        if raw is not None:
            for i, tree in enumerate(self.models):
                add[i % k] += tree.predict(raw)
            if self._track_train_leaf:
                leaf_idx = [t.predict_leaf_index(raw).astype(dt)
                            for t in self.models]
        else:
            # streamed dataset (docs/Streaming.md): the raw matrix is
            # out-of-core by design — replay the seed trees CHUNK by
            # chunk off the raw source (tree predict is row-wise, so
            # the chunked replay is exact)
            src = getattr(self.train_set, "raw_source", None)
            info = getattr(self.train_set, "stream", None)
            if src is None or info is None:
                Log.fatal("continue-training requires the training "
                          "set's raw matrix (free_raw_data=False)")
            from ..io.cache import chunk_grid
            parts: List[List[np.ndarray]] = [[] for _ in self.models] \
                if self._track_train_leaf else []
            for start, stop in chunk_grid(self.num_data,
                                          info.chunk_rows):
                blk = src.read_rows(start, stop)
                for i, tree in enumerate(self.models):
                    add[i % k, start:stop] += tree.predict(blk)
                    if self._track_train_leaf:
                        parts[i].append(
                            tree.predict_leaf_index(blk).astype(dt))
            if self._track_train_leaf:
                leaf_idx = [np.concatenate(p) for p in parts]
        self._score = self._score + jnp.asarray(
            np.pad(add, ((0, 0), (0, self._score.shape[1] - add.shape[1]))))
        if self._track_train_leaf:
            # DART needs per-tree train-leaf assignments to drop and
            # renormalize the seeded trees
            self._train_leaf_idx = leaf_idx

    def refit(self, X: np.ndarray, y: np.ndarray, weight=None,
              decay_rate: float = 0.9) -> None:
        """Refit the existing trees' leaf values to new data
        (``GBDT::RefitTree``, ``gbdt.cpp:265``;
        ``SerialTreeLearner::FitByExistingTree``,
        ``serial_tree_learner.cpp:223-252``): keep every tree's
        structure, recompute each leaf's output from the new data's
        gradient statistics at that leaf, and blend
        ``decay_rate*old + (1-decay_rate)*new``."""
        if self.objective is None:
            Log.fatal("refit requires a built-in objective")
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        n = X.shape[0]
        meta = Metadata(n)
        meta.set_label(np.asarray(y, np.float64).reshape(-1))
        if weight is not None:
            meta.set_weight(weight)
        # a FRESH objective bound to the refit data — the training
        # objective must stay bound to the train set (the reference's
        # RefitTree reuses the training gradients buffer, but its
        # objective is naturally re-pointed via leaf_pred; ours is
        # stateful over Metadata)
        objective = create_objective(self.config.objective, self.config)
        objective.init(meta, n)
        # per-tree leaf assignment of the new data (rows, n_trees)
        leaf_pred = np.stack([t.predict_leaf_index(X)
                              for t in self.models], axis=1)
        self._refit_core(leaf_pred, objective, n, decay_rate)

    def refit_leaf_preds(self, leaf_pred: np.ndarray,
                         decay_rate: float = 0.9) -> None:
        """C-API refit (``LGBM_BoosterRefit``, ``c_api.h:446``): leaf
        assignments are supplied by the caller and the gradients come
        from the TRAINING set's objective (``GBDT::RefitTree``)."""
        if self.objective is None:
            Log.fatal("refit requires a built-in objective")
        if self.train_set is None:
            Log.fatal("refit by leaf predictions needs the training set")
        n = self.num_data
        leaf_pred = np.asarray(leaf_pred, np.int32).reshape(n, -1)
        if leaf_pred.shape[1] != len(self.models):
            Log.fatal("leaf_preds has %d columns but the model has %d "
                      "trees", leaf_pred.shape[1], len(self.models))
        objective = create_objective(self.config.objective, self.config)
        objective.init(self.train_set.metadata, n)
        self._refit_core(leaf_pred, objective, n, decay_rate)

    def _refit_core(self, leaf_pred: np.ndarray, objective, n: int,
                    decay_rate: float) -> None:
        from ..ops.split import EPS
        import jax.numpy as jnp
        self._invalidate_predictor()    # leaf values mutate in place
        k = max(self.num_tree_per_iteration, 1)
        score = jnp.zeros((k, n), jnp.float32)
        cfg = self.config
        n_iters = len(self.models) // k
        for it in range(n_iters):
            g, h = objective.get_gradients(score)
            g = np.atleast_2d(np.asarray(g))
            h = np.atleast_2d(np.asarray(h))
            for tree_id in range(k):
                mi = it * k + tree_id
                tree = self.models[mi]
                lp = leaf_pred[:, mi]
                nl = tree.num_leaves
                sg = np.bincount(lp, weights=g[tree_id], minlength=nl)
                sh = np.bincount(lp, weights=h[tree_id],
                                 minlength=nl) + EPS
                out = -_threshold_l1(sg, cfg.lambda_l1) / \
                    (sh + cfg.lambda_l2)
                if cfg.max_delta_step > 0:
                    out = np.clip(out, -cfg.max_delta_step,
                                  cfg.max_delta_step)
                new_out = out * tree.shrinkage
                tree.leaf_value[:nl] = (decay_rate * tree.leaf_value[:nl]
                                        + (1.0 - decay_rate) * new_out)
                score = score.at[tree_id].add(
                    jnp.asarray(tree.leaf_value[lp], jnp.float32))

    def merge_from(self, other: "GBDT") -> None:
        """Merge another booster's trees in FRONT of this one's
        (``GBDT::MergeFrom``, ``src/boosting/gbdt.h:54``) — the parallel
        model-merge workflow's primitive.  Scores become stale relative
        to the merged ensemble, matching the reference (which also only
        splices the model list)."""
        import copy
        if other.num_tree_per_iteration != self.num_tree_per_iteration:
            Log.fatal("cannot merge boosters with different "
                      "num_tree_per_iteration")
        self.models = [copy.deepcopy(t) for t in other.models] + self.models
        self.iter = len(self.models) // max(self.num_tree_per_iteration, 1)

    def shuffle_models(self, start_iter: int = 0,
                       end_iter: int = -1) -> None:
        """Permute whole iterations in [start_iter, end_iter)
        (``GBDT::ShuffleModels``, ``src/boosting/gbdt.h:73``; fixed seed
        17 like the reference's ``Random tmp_rand(17)``)."""
        k = max(self.num_tree_per_iteration, 1)
        total_iter = len(self.models) // k
        start_iter = max(0, start_iter)
        end_iter = total_iter if end_iter <= 0 else min(total_iter,
                                                        end_iter)
        idx = np.arange(total_iter)
        rng = np.random.RandomState(17)
        span = idx[start_iter:end_iter]
        rng.shuffle(span)
        idx[start_iter:end_iter] = span
        self.models = [self.models[i * k + j] for i in idx
                       for j in range(k)]

    def rollback_one_iter(self) -> None:
        """Undo the last iteration (``GBDT::RollbackOneIter``): train
        score from the pre-iteration snapshot; valid scores by
        SUBTRACTING the popped trees' predictions (the reference's
        ``Shrinkage(-1)`` + ``AddScore``) — per-iteration valid-score
        copies were dropped from the hot loop.  A subclass that still
        snapshots (RF's multiplicative averaging) restores from
        ``_prev_valid_scores`` instead."""
        blk = getattr(self, "_fused_block", None)
        if blk is not None and blk["served"] > 0:
            self._fused_rollback()
            return
        if self.iter <= 0 or self._prev_score is None:
            return
        # materialize any in-flight tree FIRST: its flush mutates score
        # (init-score bias) and may set the stop flag — both must land
        # before the rollback restores/clears them
        self._flush_pending()
        self._stop_flag = False  # the popped tree may have set it
        # pop-then-retrain restores the tree COUNT, so the count-keyed
        # flattened-predictor cache must be version-bumped explicitly
        self._invalidate_predictor()
        self._score = self._prev_score
        if self._prev_valid_scores:
            for vs, snap in zip(self.valid_sets, self._prev_valid_scores):
                vs.score = snap
        elif self.valid_sets:
            # subtract the iteration's trees: tree.predict includes any
            # absorbed init bias, which the forward path added to the
            # valid score separately (bias + raw contribution = the
            # biased prediction), so one subtraction undoes both
            k = max(self.num_tree_per_iteration, 1)
            models = self.models  # flushed above; property is safe
            for j in range(k):
                tree = models[-1 - j]
                tree_idx = (len(models) - 1 - j) % k
                for vs in self.valid_sets:
                    vs.score[tree_idx] -= tree.predict(vs.raw)
        self._prev_score = None
        for _ in range(self.num_tree_per_iteration):
            self.models.pop()
            if self._track_train_leaf:
                for vs in self.valid_sets:
                    if vs.leaf_idx_per_tree:
                        vs.leaf_idx_per_tree.pop()
        self.iter -= 1
