"""Decision tree model object.

Capability parity with the reference's ``include/LightGBM/tree.h:20`` /
``src/io/tree.cpp``: a flat struct-of-arrays tree with per-internal-node
split feature / bin & real thresholds / gain / decision flags and per-leaf
outputs, batch prediction, shrinkage, and text / JSON serialization in the
reference's model format (``src/boosting/gbdt_model_text.cpp``) so that
models are interchangeable with the reference implementation.

Node encoding: internal nodes are numbered ``0 .. num_leaves-2``; child
pointers that are negative encode leaves as ``~leaf_index`` (two's-complement
bitwise-not), the same scheme the reference uses.

decision_type bit layout (``tree.h`` decision_type_):
  bit 0: categorical split
  bit 1: default_left (missing goes left)
  bits 2-3: missing type (0=None, 1=Zero, 2=NaN)
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

_KZERO_THRESHOLD = 1e-35

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

_CAT_MASK = 1
_DEFAULT_LEFT_MASK = 2


class Tree:
    """A trained decision tree (host-side numpy struct-of-arrays)."""

    def __init__(self, max_leaves: int):
        self.max_leaves = int(max_leaves)
        n_inner = max(self.max_leaves - 1, 1)
        self.num_leaves = 1
        self.num_cat = 0
        # per internal node
        self.split_feature = np.zeros(n_inner, dtype=np.int32)
        self.split_gain = np.zeros(n_inner, dtype=np.float64)
        self.threshold = np.zeros(n_inner, dtype=np.float64)   # real value
        self.threshold_bin = np.zeros(n_inner, dtype=np.int32)  # bin id
        self.decision_type = np.zeros(n_inner, dtype=np.int8)
        self.left_child = np.zeros(n_inner, dtype=np.int32)
        self.right_child = np.zeros(n_inner, dtype=np.int32)
        self.internal_value = np.zeros(n_inner, dtype=np.float64)
        self.internal_weight = np.zeros(n_inner, dtype=np.float64)
        self.internal_count = np.zeros(n_inner, dtype=np.int64)
        # per leaf
        self.leaf_value = np.zeros(self.max_leaves, dtype=np.float64)
        self.leaf_weight = np.zeros(self.max_leaves, dtype=np.float64)
        self.leaf_count = np.zeros(self.max_leaves, dtype=np.int64)
        self.leaf_parent = np.full(self.max_leaves, -1, dtype=np.int32)
        self.leaf_depth = np.zeros(self.max_leaves, dtype=np.int32)
        # categorical split storage: thresholds are bitsets of category ids;
        # node i with categorical split uses words
        # cat_threshold[cat_boundaries[k]:cat_boundaries[k+1]] where
        # k = int(threshold[i])
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []
        self.shrinkage = 1.0

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    def split(self, leaf: int, feature: int, threshold_bin: int,
              threshold_real: float, left_value: float, right_value: float,
              left_weight: float, right_weight: float,
              left_count: int, right_count: int,
              gain: float, missing_type: int, default_left: bool) -> int:
        """Numerical split of ``leaf``; returns the new (right) leaf index.

        Mirrors ``Tree::Split`` (``src/io/tree.cpp:51``): the left child
        keeps the parent's leaf index, the right child becomes leaf
        ``num_leaves``.
        """
        new_node = self.num_leaves - 1
        new_leaf = self.num_leaves
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature[new_node] = feature
        self.split_gain[new_node] = gain
        self.threshold[new_node] = threshold_real
        self.threshold_bin[new_node] = threshold_bin
        dt = (missing_type << 2)
        if default_left:
            dt |= _DEFAULT_LEFT_MASK
        self.decision_type[new_node] = dt
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~new_leaf
        self.internal_value[new_node] = self.leaf_value[leaf]
        self.internal_weight[new_node] = left_weight + right_weight
        self.internal_count[new_node] = left_count + right_count
        depth = self.leaf_depth[leaf] + 1
        self.leaf_value[leaf] = left_value
        self.leaf_weight[leaf] = left_weight
        self.leaf_count[leaf] = left_count
        self.leaf_parent[leaf] = new_node
        self.leaf_depth[leaf] = depth
        self.leaf_value[new_leaf] = right_value
        self.leaf_weight[new_leaf] = right_weight
        self.leaf_count[new_leaf] = right_count
        self.leaf_parent[new_leaf] = new_node
        self.leaf_depth[new_leaf] = depth
        self.num_leaves += 1
        return new_leaf

    def split_categorical(self, leaf: int, feature: int, cat_bitset: List[int],
                          left_value: float, right_value: float,
                          left_weight: float, right_weight: float,
                          left_count: int, right_count: int,
                          gain: float, missing_type: int) -> int:
        """Categorical split: left iff category in bitset
        (``Tree::SplitCategorical``, ``src/io/tree.cpp:72``)."""
        new_leaf = self.split(leaf, feature, 0, 0.0, left_value, right_value,
                              left_weight, right_weight, left_count,
                              right_count, gain, missing_type, False)
        node = self.num_leaves - 2
        self.decision_type[node] |= _CAT_MASK
        self.threshold[node] = float(self.num_cat)
        self.threshold_bin[node] = self.num_cat
        self.cat_threshold.extend(cat_bitset)
        self.cat_boundaries.append(len(self.cat_threshold))
        self.num_cat += 1
        return new_leaf

    def apply_shrinkage(self, rate: float) -> None:
        self.leaf_value[:self.num_leaves] *= rate
        self.internal_value[:max(self.num_leaves - 1, 1)] *= rate
        self.shrinkage *= rate

    def add_bias(self, bias: float) -> None:
        self.leaf_value[:self.num_leaves] += bias
        self.internal_value[:max(self.num_leaves - 1, 1)] += bias

    def set_leaf_values(self, values: np.ndarray) -> None:
        self.leaf_value[:self.num_leaves] = values[:self.num_leaves]

    # ------------------------------------------------------------------
    # prediction (vectorized numpy; device paths live in ops/)
    # ------------------------------------------------------------------
    def _decide(self, node: np.ndarray, values: np.ndarray) -> np.ndarray:
        """Return boolean go-left for rows at internal ``node`` with raw
        feature ``values`` (``Tree::NumericalDecision`` /
        ``CategoricalDecision``)."""
        dt = self.decision_type[node]
        is_cat = (dt & _CAT_MASK) != 0
        missing_type = (dt >> 2) & 3
        default_left = (dt & _DEFAULT_LEFT_MASK) != 0
        nan_mask = np.isnan(values)
        zero_mask = np.abs(values) <= _KZERO_THRESHOLD
        out = np.zeros(values.shape, dtype=bool)

        num = ~is_cat
        if np.any(num):
            v = values[num]
            thr = self.threshold[node[num]]
            mt = missing_type[num]
            dl = default_left[num]
            vnan = nan_mask[num]
            # MissingType::None or Zero: NaN is treated as 0
            v = np.where(vnan & (mt != MISSING_NAN), 0.0, v)
            miss = np.where(mt == MISSING_NAN, vnan,
                            np.where(mt == MISSING_ZERO,
                                     zero_mask[num] | vnan, False))
            left = np.where(np.isnan(v), False, v <= thr)
            out[num] = np.where(miss, dl, left)
        if np.any(is_cat):
            v = values[is_cat]
            cat = np.where(nan_mask[is_cat], -1, v).astype(np.float64)
            cat = np.where(np.isfinite(cat), cat, -1)
            icat = cat.astype(np.int64)
            icat = np.where((icat < 0) | (cat != icat), -1, icat)
            goes = np.zeros(len(v), dtype=bool)
            kidx = self.threshold_bin[node[is_cat]]
            for j in range(len(v)):
                c = icat[j]
                if c < 0:
                    continue
                k = kidx[j]
                lo, hi = self.cat_boundaries[k], self.cat_boundaries[k + 1]
                w, b = divmod(int(c), 32)
                if w < hi - lo and (self.cat_threshold[lo + w] >> b) & 1:
                    goes[j] = True
            out[is_cat] = goes
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Batch leaf-value prediction on raw features (rows, features).

        This single-tree numpy traversal is the ORACLE for the
        ensemble-flattened jitted engine (``ops/predict.py``), which
        serves the production ``GBDT.predict*`` paths; the node-table
        round-trip ``flatten(tree) -> traverse == tree.predict`` is
        pinned in ``tests/test_tree.py``."""
        return self.leaf_value[self.predict_leaf_index(X)]

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = np.ones(n, dtype=bool)
        leaf = np.zeros(n, dtype=np.int32)
        while np.any(active):
            idx = np.where(active)[0]
            cur = node[idx]
            vals = X[idx, self.split_feature[cur]].astype(np.float64)
            left = self._decide(cur, vals)
            nxt = np.where(left, self.left_child[cur], self.right_child[cur])
            is_leaf = nxt < 0
            leaf[idx[is_leaf]] = ~nxt[is_leaf]
            active[idx[is_leaf]] = False
            node[idx[~is_leaf]] = nxt[~is_leaf]
        return leaf

    def depth(self) -> int:
        return int(self.leaf_depth[:self.num_leaves].max()) if self.num_leaves > 1 else 0

    # ------------------------------------------------------------------
    # serialization — reference text model format
    # ------------------------------------------------------------------
    def _arr_str(self, arr, n, fmt=None) -> str:
        if fmt is None:
            return " ".join(str(x) for x in arr[:n])
        return " ".join(fmt % x for x in arr[:n])

    def to_string(self, index: int) -> str:
        n_inner = self.num_leaves - 1
        lines = [f"Tree={index}",
                 f"num_leaves={self.num_leaves}",
                 f"num_cat={self.num_cat}"]
        if n_inner > 0:
            lines += [
                "split_feature=" + self._arr_str(self.split_feature, n_inner),
                "split_gain=" + self._arr_str(self.split_gain, n_inner, "%g"),
                "threshold=" + self._arr_str(self.threshold, n_inner, "%.17g"),
                "decision_type=" + self._arr_str(self.decision_type, n_inner),
                "left_child=" + self._arr_str(self.left_child, n_inner),
                "right_child=" + self._arr_str(self.right_child, n_inner),
                "leaf_value=" + self._arr_str(self.leaf_value,
                                              self.num_leaves, "%.17g"),
                "leaf_weight=" + self._arr_str(self.leaf_weight,
                                               self.num_leaves, "%g"),
                "leaf_count=" + self._arr_str(self.leaf_count,
                                              self.num_leaves),
                "internal_value=" + self._arr_str(self.internal_value,
                                                  n_inner, "%g"),
                "internal_weight=" + self._arr_str(self.internal_weight,
                                                   n_inner, "%g"),
                "internal_count=" + self._arr_str(self.internal_count,
                                                  n_inner),
            ]
            if self.num_cat > 0:
                lines += [
                    "cat_boundaries=" + " ".join(map(str, self.cat_boundaries)),
                    "cat_threshold=" + " ".join(map(str, self.cat_threshold)),
                ]
        else:
            lines += ["leaf_value=" + self._arr_str(self.leaf_value, 1,
                                                    "%.17g")]
        lines.append(f"shrinkage={self.shrinkage:g}")
        return "\n".join(lines) + "\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in text.splitlines():
            line = line.strip()
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
        num_leaves = int(kv["num_leaves"])
        tree = cls(max(num_leaves, 2))
        tree.num_leaves = num_leaves
        tree.num_cat = int(kv.get("num_cat", "0"))
        n_inner = num_leaves - 1

        def arr(key, dtype, n):
            if key not in kv or n == 0:
                return None
            vals = np.array(kv[key].split(), dtype=np.float64)
            return vals[:n].astype(dtype)

        if n_inner > 0:
            for key, attr, dtype in [
                    ("split_feature", "split_feature", np.int32),
                    ("split_gain", "split_gain", np.float64),
                    ("threshold", "threshold", np.float64),
                    ("decision_type", "decision_type", np.int8),
                    ("left_child", "left_child", np.int32),
                    ("right_child", "right_child", np.int32),
                    ("internal_value", "internal_value", np.float64),
                    ("internal_weight", "internal_weight", np.float64),
                    ("internal_count", "internal_count", np.int64)]:
                v = arr(key, dtype, n_inner)
                if v is not None:
                    getattr(tree, attr)[:n_inner] = v
            tree.threshold_bin[:n_inner] = tree.threshold[:n_inner].astype(
                np.int32)
            for key, attr, dtype in [
                    ("leaf_value", "leaf_value", np.float64),
                    ("leaf_weight", "leaf_weight", np.float64),
                    ("leaf_count", "leaf_count", np.int64)]:
                v = arr(key, dtype, num_leaves)
                if v is not None:
                    getattr(tree, attr)[:num_leaves] = v
            if tree.num_cat > 0:
                tree.cat_boundaries = [int(x) for x in
                                       kv["cat_boundaries"].split()]
                tree.cat_threshold = [int(x) for x in
                                      kv["cat_threshold"].split()]
            # recover leaf_parent / leaf_depth from children
            tree._rebuild_parents()
        else:
            tree.leaf_value[0] = float(kv["leaf_value"].split()[0])
        tree.shrinkage = float(kv.get("shrinkage", "1"))
        return tree

    def _rebuild_parents(self) -> None:
        n_inner = self.num_leaves - 1
        depth = np.zeros(max(n_inner, 1), dtype=np.int32)
        for node in range(n_inner):
            for child in (self.left_child[node], self.right_child[node]):
                if child < 0:
                    self.leaf_parent[~child] = node
                    self.leaf_depth[~child] = depth[node] + 1
                else:
                    depth[child] = depth[node] + 1

    def to_json(self, index: int) -> Dict:
        def node_json(node_idx: int) -> Dict:
            if node_idx < 0:
                leaf = ~node_idx
                return {"leaf_index": int(leaf),
                        "leaf_value": float(self.leaf_value[leaf]),
                        "leaf_weight": float(self.leaf_weight[leaf]),
                        "leaf_count": int(self.leaf_count[leaf])}
            dt = int(self.decision_type[node_idx])
            is_cat = bool(dt & _CAT_MASK)
            mt = (dt >> 2) & 3
            d = {"split_index": int(node_idx),
                 "split_feature": int(self.split_feature[node_idx]),
                 "split_gain": float(self.split_gain[node_idx]),
                 "threshold": (self._cat_list(self.threshold_bin[node_idx])
                               if is_cat else float(self.threshold[node_idx])),
                 "decision_type": "==" if is_cat else "<=",
                 "default_left": bool(dt & _DEFAULT_LEFT_MASK),
                 "missing_type": ["None", "Zero", "NaN"][mt],
                 "internal_value": float(self.internal_value[node_idx]),
                 "internal_weight": float(self.internal_weight[node_idx]),
                 "internal_count": int(self.internal_count[node_idx]),
                 "left_child": node_json(int(self.left_child[node_idx])),
                 "right_child": node_json(int(self.right_child[node_idx]))}
            return d
        if self.num_leaves <= 1:
            structure = {"leaf_value": float(self.leaf_value[0])}
        else:
            structure = node_json(0)
        return {"tree_index": int(index), "num_leaves": int(self.num_leaves),
                "num_cat": int(self.num_cat),
                "shrinkage": float(self.shrinkage),
                "tree_structure": structure}

    def _cat_list(self, k: int) -> List[int]:
        lo, hi = self.cat_boundaries[k], self.cat_boundaries[k + 1]
        cats = []
        for w in range(lo, hi):
            word = self.cat_threshold[w]
            for b in range(32):
                if (word >> b) & 1:
                    cats.append((w - lo) * 32 + b)
        return cats

    def __repr__(self) -> str:
        return (f"Tree(num_leaves={self.num_leaves}, depth={self.depth()}, "
                f"shrinkage={self.shrinkage})")


def cat_bitset(categories) -> List[int]:
    """Build a 32-bit-word bitset from category bin ids
    (``Common::ConstructBitset`` equivalent)."""
    if len(categories) == 0:
        return [0]
    n_words = int(max(categories)) // 32 + 1
    words = [0] * n_words
    for c in categories:
        words[int(c) // 32] |= 1 << (int(c) % 32)
    return words
