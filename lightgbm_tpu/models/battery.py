"""Many-model battery training: B independent boosters in ONE
compiled program.

The fused super-step (``gbdt.py``) trains exactly one booster per
compiled scan.  The battery lifts that SAME scan over a leading model
axis with ``jax.vmap``: the shared binned matrix stays resident once,
per-model state (scores, bagging carries, learning rates, PRNG keys,
per-iteration feature masks, fold weights) is stacked on axis 0, and
one XLA program trains every member — k-fold CV and hyperparameter
sweeps stop paying B compiles and B dispatch streams for B models
(ROADMAP item 4; the same amortize-the-host-boundary move that made
single-model training fast).

Bit-exactness contract: every battery member's trees are byte-equal to
the same params trained solo (pinned by ``tests/test_sweep.py``).  The
anchors:

- ``_superstep_core(batched=True)`` is the solo scan body verbatim;
  per-model values enter as TRACED leading-axis operands while every
  program-shaping knob stays static, so vmap adds a batch dimension
  without touching the per-member expression tree.
- CV fold masks ride as the objective's per-row weight
  (``Objective.weight_override``), multiplying at exactly the point
  solo weighted training multiplies metadata weights.  Unweighted
  members ride a unit vector — ``x * 1.0`` is bitwise ``x``.
- PRNG independence: member ``i``'s bagging/GOSS/MVS stream is
  ``fold_in(PRNGKey(seed_i), global_iter)`` and its quantization
  stream ``fold_in(PRNGKey(qseed_i), tree_id)`` — a pure function of
  ITS seeds and the global counters, unchanged by B.
- Host feature-fraction draws replay each member's solo
  ``RandomState`` stream in iteration order.

Members whose resolved configs agree on everything but the traced
per-model values (learning rate, seeds, feature_fraction, weights)
share one compiled program; a sweep over those knobs costs ONE XLA
compile however many members it has.  Members the fused scan cannot
express (DART/RF, distributed learners, objectives with leaf-renewal
hooks or baked-in weights) fall back to per-member solo training —
same results, no shared compile.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils.log import Log
from .tree import Tree
from .gbdt import _KEPS, records_to_tree

__all__ = ["MemberSpec", "MemberResult", "BatteryReport",
           "train_battery", "objective_string", "member_model_string"]

# params that ride the batched program as TRACED per-model operands —
# members differing only in these share one compiled program.  Every
# other param shapes the program (tree topology, sampling structure,
# scan length, ...) and splits the battery into static groups.
TRACED_EXEMPT = frozenset({
    "learning_rate", "shrinkage_rate", "eta",
    "bagging_seed", "bagging_fraction_seed",
    "feature_fraction_seed",
    "data_random_seed",
    "feature_fraction", "sub_feature", "colsample_bytree",
})


@dataclasses.dataclass
class MemberSpec:
    """One battery member: a full param dict plus optional per-row
    training weights (CV fold masks — the COMPLETE effective weight,
    i.e. already multiplied with any dataset weight) and an optional
    boolean row mask scored for the eval curve."""
    params: Dict[str, Any]
    weight: Optional[np.ndarray] = None
    eval_mask: Optional[np.ndarray] = None
    tag: str = ""


@dataclasses.dataclass
class MemberResult:
    spec: MemberSpec
    trees: List[Tree] = dataclasses.field(default_factory=list)
    init_score: float = 0.0
    curve: Optional[List[float]] = None   # per-iteration eval metric
    stopped_at: Optional[int] = None      # iteration of the stop tree
    lane: str = "vmap"                    # vmap | solo
    failed: bool = False
    error: str = ""
    num_tree_per_iteration: int = 1
    average_output: bool = False  # RF: trees average instead of sum


@dataclasses.dataclass
class BatteryReport:
    results: List[MemberResult]
    groups: int = 0                 # static-signature groups (vmap lane)
    vmap_members: int = 0
    solo_members: int = 0
    xla_compiles: int = 0           # compile delta across vmap dispatches
    expected_compiles: int = 0      # == groups when nothing retraced
    duration_s: float = 0.0

    @property
    def retraces_per_model(self) -> float:
        if self.vmap_members <= 0:
            return 0.0
        return max(0, self.xla_compiles - self.expected_compiles) \
            / float(self.vmap_members)


def objective_string(config) -> str:
    """Model-file objective line for a config — mirrors
    ``basic.Booster._objective_string`` so battery exports are
    byte-equal to solo booster exports."""
    obj = config.objective
    if obj in ("none", "custom", "null", "na"):
        return ""
    if obj == "binary":
        return f"binary sigmoid:{config.sigmoid:g}"
    if obj in ("multiclass", "multiclassova"):
        return f"{obj} num_class:{config.num_class}"
    if obj == "lambdarank":
        return "lambdarank"
    return obj


def member_model_string(result: MemberResult, config, train_set,
                        num_iteration: int = -1) -> str:
    """Serialize one member's trees exactly as
    ``Booster.model_to_string`` would (same header fields, same
    truncation semantics) — the export path for sweep winners."""
    from . import model_io
    return model_io.save_model_to_string(
        result.trees, num_class=int(getattr(config, "num_class", 1) or 1),
        num_tree_per_iteration=result.num_tree_per_iteration,
        label_index=0,
        max_feature_idx=train_set.num_total_features - 1,
        objective_str=objective_string(config),
        feature_names=train_set.feature_names,
        feature_infos=train_set.feature_infos(),
        num_iteration=num_iteration, parameters="",
        average_output=result.average_output)


# ----------------------------------------------------------------------
def _group_key(spec: MemberSpec):
    return tuple(sorted((k, repr(v)) for k, v in spec.params.items()
                        if k not in TRACED_EXEMPT))


class _MetaView:
    """Metadata facade with an overridden weight — what a per-member
    objective instance init()s against so its host-side
    ``boost_from_score`` sees exactly the weights the solo reference
    (dataset weight = fold mask) would."""

    def __init__(self, md, weight):
        self.num_data = md.num_data
        self.label = md.label
        self.weight = weight
        self.query_boundaries = md.query_boundaries
        self.init_score = md.init_score


def _vmap_lane_ok(gbdt) -> Optional[str]:
    """None when the fused scan can express this member's whole
    training run; otherwise the gate that rejected it (the solo
    fallback reason)."""
    from ..objectives import Objective
    if not getattr(gbdt, "_superstep_enabled", False):
        return "boosting mode opts out of the fused scan"
    if gbdt.num_tree_per_iteration != 1:
        return "multiclass trains k trees per iteration"
    if gbdt.objective is None:
        return "custom objective supplies gradients"
    if gbdt.num_features == 0:
        return "no usable features"
    if type(gbdt.objective).renew_tree_output is not \
            Objective.renew_tree_output:
        return "objective renews leaf outputs on host"
    if gbdt.objective.gradient_fn() is None:
        return "objective opted out of the pure gradient contract"
    if gbdt._dist is not None:
        return "distributed tree learner owns the mesh"
    if not gbdt.objective.supports_weight_override:
        return "objective bakes weights in at init"
    if gbdt.grow_params.split.has_monotone:
        # the monotone gain recompute reassociates under a batch axis
        # (cancellation-amplified ULP drift in recorded split gains)
        return "monotone gain recompute is not bit-stable under vmap"
    return None


def _feature_masks(gbdt, config, T: int) -> np.ndarray:
    """Replay one member's host feature-fraction stream: T draws in
    iteration order from the member's own RandomState — exactly the
    solo ``_feature_fraction_mask`` consumption."""
    rng = np.random.RandomState(config.feature_fraction_seed & 0x7FFFFFFF)
    F, F_pad = gbdt.num_features, gbdt._F_pad
    frac = config.feature_fraction
    masks = np.zeros((T, F_pad), bool)
    for t in range(T):
        if frac >= 1.0:
            masks[t, :F] = True
        else:
            k = max(1, int(frac * F))
            masks[t, rng.choice(F, size=k, replace=False)] = True
    return masks


def _model_mesh(B: int):
    """A 1-D mesh over ALL devices for the model axis, or None when it
    cannot tile B members evenly (the vmap lane then runs unsharded on
    one device — never a silent wrong answer, members are
    independent)."""
    import jax
    devs = jax.devices()
    if len(devs) <= 1 or B % len(devs) != 0:
        return None
    from jax.sharding import Mesh
    return Mesh(np.asarray(devs), ("battery",))


def _train_group_vmapped(dataset, specs: Sequence[MemberSpec],
                         results: Dict[int, MemberResult],
                         indices: Sequence[int],
                         metric: Optional[Callable],
                         shard_models: bool,
                         compile_counter: List[int]) -> None:
    """Train one static-signature group of B members through a single
    vmapped (optionally model-sharded) dispatch of the fused scan."""
    import jax
    import jax.numpy as jnp
    from ..basic import Booster
    from ..config import Config
    from ..objectives import create_objective
    from ..utils import telemetry as _telemetry

    template = Booster(params=dict(specs[0].params), train_set=dataset)
    gbdt = template._gbdt
    tds = dataset._constructed
    md = tds.metadata
    B = len(specs)
    n = gbdt.num_data
    cfgs = [gbdt.config] + [Config(dict(s.params)) for s in specs[1:]]
    T = int(gbdt.config.num_iterations)
    quantize = bool(gbdt.grow_params.quantize)

    # ---- per-member stacks -------------------------------------------
    base_score = np.asarray(gbdt._score)          # (k, n) f32: 0 + init
    score0 = np.repeat(base_score[None], B, axis=0)
    inits = np.zeros(B, np.float64)
    wvec = np.ones((B, n), np.float32)
    lr = np.zeros(B, np.float32)
    fmasks = np.zeros((B, T, gbdt._F_pad), bool)
    bag_keys = np.zeros((B, 2), np.uint32)
    quant_keys = np.zeros((B, 2), np.uint32)
    qk0 = np.asarray(jax.random.PRNGKey(0))
    for b, (spec, cfg) in enumerate(zip(specs, cfgs)):
        lr[b] = np.float32(cfg.learning_rate)
        fmasks[b] = _feature_masks(gbdt, cfg, T)
        bag_keys[b] = np.asarray(
            jax.random.PRNGKey(cfg.bagging_seed & 0x7FFFFFFF))
        quant_keys[b] = (np.asarray(jax.random.PRNGKey(
            cfg.data_random_seed & 0x7FFFFFFF)) if quantize else qk0)
        if spec.weight is not None:
            wvec[b] = np.asarray(spec.weight, np.float32).reshape(-1)
        elif md.weight is not None:
            wvec[b] = np.asarray(md.weight, np.float32).reshape(-1)
        # boost_from_average: solo runs iteration 0 unfused with the
        # bias pre-added to the score and absorbed by tree 0; the
        # battery pre-adds it on host (f32 add — same IEEE op as the
        # device .add) and absorbs it at materialization
        if (cfg.boost_from_average and md.init_score is None and
                gbdt.num_features > 0):
            w_view = (np.asarray(spec.weight, np.float32).reshape(-1)
                      if spec.weight is not None else md.weight)
            obj_b = create_objective(cfg.objective, cfg)
            obj_b.init(_MetaView(md, w_view), n)
            init = float(obj_b.boost_from_score(0))
            if abs(init) > _KEPS:
                inits[b] = init
                score0[b, 0, :] += np.float32(init)

    iters = jnp.arange(0, T, dtype=jnp.int32)
    tree_ids = jnp.arange(0, T, dtype=jnp.int32)
    bag0 = jnp.ones((B, n), jnp.float32)

    # ---- one compiled program for the whole group --------------------
    core = gbdt._superstep_core(batched=True)
    fn = jax.vmap(core, in_axes=(0, 0, 0, 0, None, None, None, None,
                                 None, None, 0, None, 0, 0))
    mesh = _model_mesh(B) if shard_models else None
    if mesh is not None:
        # model-axis sharding: members are embarrassingly parallel, so
        # every per-member operand splits on its leading axis and the
        # shared dataset replicates — no collectives, hence the exact
        # same per-member program (parity preserved by construction)
        from jax.sharding import PartitionSpec as P
        from ..parallel.learners import shard_map_compat
        Pb, R = P("battery"), P()
        in_specs = (Pb, Pb, Pb, Pb, R, R, R, R, R, R, Pb, R, Pb, Pb)
        fn = shard_map_compat(
            fn, mesh, in_specs=in_specs,
            out_specs=(Pb, Pb, Pb, Pb, Pb, Pb, Pb))
    fn = jax.jit(fn)

    args = (jnp.asarray(score0), bag0, jnp.asarray(lr),
            jnp.asarray(quant_keys),
            gbdt._xt, gbdt._base_mask, gbdt._num_bins,
            gbdt._missing_type, gbdt._is_cat, iters,
            jnp.asarray(fmasks), tree_ids, jnp.asarray(wvec),
            jnp.asarray(bag_keys))
    if mesh is not None:
        # pre-place operands on the mesh so the one-time input layout
        # (split / replicate) transfer programs compile OUTSIDE the
        # retrace bracket below — they are per-shape data movement, not
        # retraces of the member program
        from jax.sharding import NamedSharding
        args = tuple(jax.device_put(a, NamedSharding(mesh, s))
                     for a, s in zip(args, in_specs))
        jax.block_until_ready(args)
    _telemetry.install_jax_hooks()
    pre = _telemetry.counters.snapshot().get("xla_compiles", 0)
    outs = fn(*args)
    jax.block_until_ready(outs[2])
    post = _telemetry.counters.snapshot().get("xla_compiles", 0)
    compile_counter[0] += int(post - pre)
    _telemetry.counters.incr("battery_dispatches")

    # ---- one packed fetch, then per-member host materialization ------
    host = gbdt._fetch_records(outs[4])            # (B, K, ...) stacks
    leaf_idx_k = np.asarray(outs[5])               # (B, K, n) narrow
    vals_k = np.asarray(outs[6])                   # (B, K, num_leaves)
    bad = np.asarray(host.pop("nonfinite", np.zeros((B, T))), bool)
    n_leaves = np.asarray(host["n_leaves"])

    for b, (spec, cfg) in enumerate(zip(specs, cfgs)):
        res = results[indices[b]]
        res.lane = "vmap"
        res.init_score = float(inits[b])
        rows = (np.nonzero(np.asarray(spec.eval_mask).reshape(-1))[0]
                if spec.eval_mask is not None else None)
        sc = score0[b, 0, rows].copy() if rows is not None else None
        curve: List[float] = []
        trees: List[Tree] = []
        for t in range(T):
            stop = int(n_leaves[b, t]) <= 1
            if bad[b, t] and not stop:
                res.failed = True
                res.error = (f"non-finite values at iteration {t} "
                             f"(member {spec.tag or b})")
                Log.warning("battery member %s: %s", spec.tag or b,
                            res.error)
                break
            if stop:
                # constant stop tree; post-stop scan iterations are
                # phantom state the replay discards (solo semantics)
                tree = Tree(2)
                if t == 0 and abs(inits[b]) > _KEPS:
                    tree.leaf_value[0] = inits[b]
                trees.append(tree)
                res.stopped_at = t
                break
            rec_t = {k: v[b, t] for k, v in host.items()}
            tree = records_to_tree(rec_t, cfg, tds,
                                   counts_proxy=getattr(
                                       gbdt, "_counts_proxy", False))
            # host shrinkage uses the config's exact f64 rate (the
            # device scan got the f32 cast) — solo does the same
            tree.apply_shrinkage(float(cfg.learning_rate))
            if t == 0 and abs(inits[b]) > _KEPS:
                tree.add_bias(inits[b])
            trees.append(tree)
            if rows is not None:
                # f32 adds per row in scan order — bit-equal to the
                # device score carry, so the CV curve scores exactly
                # the model the member trained
                sc += vals_k[b, t][leaf_idx_k[b, t][rows].astype(
                    np.int64)]
                if metric is not None:
                    curve.append(float(metric(sc, rows)))
        res.trees = trees
        res.curve = curve if rows is not None else None
        res.num_tree_per_iteration = gbdt.num_tree_per_iteration


def _train_member_solo(dataset, spec: MemberSpec, res: MemberResult,
                       metric: Optional[Callable], reason: str) -> None:
    """Fallback lane: solo-train one member on the SHARED dataset with
    its weights swapped in (and restored) — identical results to the
    vmap lane's contract, without the shared compile."""
    from ..basic import Booster

    tds = dataset._constructed
    md = tds.metadata if tds is not None else None
    saved_ds_w, saved_md_w = dataset.weight, (md.weight if md else None)
    try:
        if spec.weight is not None:
            w = np.asarray(spec.weight, np.float32).reshape(-1)
            dataset.weight = w
            if md is not None:
                md.weight = w
        bst = Booster(params=dict(spec.params), train_set=dataset)
        g = bst._gbdt
        T = int(g.config.num_iterations)
        rows = (np.nonzero(np.asarray(spec.eval_mask).reshape(-1))[0]
                if spec.eval_mask is not None else None)
        curve: List[float] = []
        for it in range(T):
            stop = bst.update()
            if rows is not None and metric is not None and not stop:
                sc = np.asarray(g._score)[0, rows]
                curve.append(float(metric(sc, rows)))
            if stop:
                res.stopped_at = it
                break
        res.trees = list(g.models)
        res.curve = curve if rows is not None else None
        res.lane = "solo"
        res.error = reason
        res.num_tree_per_iteration = g.num_tree_per_iteration
        res.average_output = bool(g.average_output)
    except Exception as exc:  # noqa: BLE001 - one member, not the sweep
        res.failed = True
        res.lane = "solo"
        res.error = f"{reason}; solo fallback raised: {exc}"
        Log.warning("battery member %s failed: %s", spec.tag, res.error)
    finally:
        dataset.weight = saved_ds_w
        if md is not None:
            md.weight = saved_md_w


def train_battery(dataset, specs: Sequence[MemberSpec], *,
                  metric: Optional[Callable] = None,
                  shard_models: bool = False) -> BatteryReport:
    """Train every member spec against one shared constructed dataset.

    ``metric``: optional ``(scores_f32, row_indices) -> float`` scored
    per iteration on each member's ``eval_mask`` rows (the CV curve).
    ``shard_models``: lay the model axis onto the device mesh when it
    tiles evenly (``sweep_shard_models``).

    Members are grouped by static signature; each group dispatches as
    ONE compiled vmapped program.  Ineligible members run the solo
    fallback lane.  Returns per-member trees/curves plus the compile
    accounting the ``sweep`` telemetry record reports."""
    from ..basic import Booster

    t0 = time.perf_counter()
    dataset.construct()
    results = {i: MemberResult(spec=s) for i, s in enumerate(specs)}
    groups: Dict[Any, List[int]] = {}
    for i, s in enumerate(specs):
        groups.setdefault(_group_key(s), []).append(i)

    compile_counter = [0]
    n_vmap_groups = 0
    vmap_members = solo_members = 0
    for key, idxs in groups.items():
        probe = Booster(params=dict(specs[idxs[0]].params),
                        train_set=dataset)
        reason = _vmap_lane_ok(probe._gbdt)
        del probe
        if reason is None:
            try:
                _train_group_vmapped(dataset, [specs[i] for i in idxs],
                                     results, idxs, metric,
                                     shard_models, compile_counter)
                n_vmap_groups += 1
                vmap_members += len(idxs)
                continue
            except Exception as exc:  # noqa: BLE001
                reason = f"vmapped dispatch failed: {exc}"
                Log.warning("battery group falls back to solo: %s",
                            reason)
        for i in idxs:
            _train_member_solo(dataset, specs[i], results[i], metric,
                               reason)
            solo_members += 1

    return BatteryReport(
        results=[results[i] for i in range(len(specs))],
        groups=n_vmap_groups, vmap_members=vmap_members,
        solo_members=solo_members, xla_compiles=compile_counter[0],
        expected_compiles=n_vmap_groups,
        duration_s=time.perf_counter() - t0)
