"""Boosting-mode variants and the boosting factory.

Capability parity with ``src/boosting/``: GOSS (``goss.hpp:26``), MVS —
the fork's signature addition (``mvs.hpp:28``), DART (``dart.hpp:17``)
and RF (``rf.hpp:18``), dispatched by ``config.boosting`` like
``Boosting::CreateBoosting`` (``boosting.cpp:33-58``).

TPU-first: sampling modes produce per-row WEIGHT vectors (0 = dropped,
>1 = upweighted) consumed by the device growth loop's masked histogram
pass, instead of the reference's index-buffer compaction — the binned
matrix never moves, only the (N,) gradient/hessian/mask vectors change.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..io.dataset import Metadata, TpuDataset
from ..objectives import Objective
from ..metrics import Metric
from ..utils.log import Log
from .gbdt import GBDT, _KEPS
from .tree import Tree


class GOSS(GBDT):
    """Gradient-based one-side sampling (``goss.hpp:26``): keep the
    ``top_rate`` rows by |g*h|, sample ``other_rate`` of the rest and
    upweight their grad/hess by (n - top_k) / other_k
    (``goss.hpp:99-128``)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        cfg = self.config
        if cfg.top_rate + cfg.other_rate > 1.0:
            Log.fatal("GOSS requires top_rate + other_rate <= 1")
        if cfg.top_rate <= 0 or cfg.other_rate <= 0:
            Log.fatal("GOSS requires top_rate > 0 and other_rate > 0")
        if cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0:
            Log.fatal("Cannot use bagging in GOSS")
        Log.info("Using GOSS")

    def _bagging_mask(self, grad=None, hess=None):
        if grad is None:
            return None
        return self._goss_mask(self.iter, grad, hess)

    def _fused_mask_fn(self):
        """GOSS inside the fused super-step: the mask is a pure device
        function of the iteration's gradients and the PRNG fold of the
        GLOBAL iteration index — bit-identical to the sequential
        draw."""
        return lambda it, prev, grad, hess: self._goss_mask(it, grad,
                                                            hess)

    def _goss_mask(self, it, grad, hess):
        """Device GOSS mask: the top set is everything above the
        ``top_rate``-quantile of |g*h| (one device sort, no host
        round-trip), the rest is a Bernoulli sample at ``other_rate``'s
        expected size — same expected composition and upweighting as
        the reference's exact argsort + without-replacement choice, in
        O(sort) device work instead of a full-N host argsort per
        iteration.  ``it`` may be a host int or a traced scalar; one
        jitted program serves the sequential and scan-inlined call
        sites (fused-path bit-parity)."""
        import jax
        if getattr(self, "_trace_raw", False):
            # battery trace: ``self._bag_key`` is a per-model tracer —
            # inline the raw impl (jit under a trace compiles to the
            # same program, so solo/battery stay bit-identical)
            return self._goss_mask_impl(it, grad, hess)
        if getattr(self, "_goss_mask_jit", None) is None:
            self._goss_mask_jit = jax.jit(self._goss_mask_impl)
        return self._goss_mask_jit(it, grad, hess)

    def _goss_mask_impl(self, it, grad, hess):
        import jax
        import jax.numpy as jnp
        cfg = self.config
        n = self.num_data
        gh = jnp.sum(jnp.abs(grad * hess), axis=0)[:n]
        top_k = max(int(n * cfg.top_rate), 1)
        other_k = int(n * cfg.other_rate)
        thr = -jnp.sort(-gh)[top_k - 1]
        key = jax.random.fold_in(self._bag_key, it)
        ku, kt = jax.random.split(key)
        # tie-safe top set: strictly-greater rows always kept, rows AT
        # the threshold admitted at the rate that fills top_k in
        # expectation — a plain gh >= thr would keep EVERY tied row
        # (e.g. the whole dataset when >top_rate of |g*h| is 0)
        gt = gh > thr
        tie = gh == thr
        n_gt = jnp.sum(gt)
        n_tie = jnp.maximum(jnp.sum(tie), 1)
        p_tie = jnp.clip((top_k - n_gt) / n_tie, 0.0, 1.0)
        topm = gt | (tie & (jax.random.uniform(kt, (n,)) < p_tie))
        u = jax.random.uniform(ku, (n,))
        n_rest = max(n - top_k, 1)
        pick = (~topm) & (u < other_k / n_rest)
        amp = (n - top_k) / float(max(other_k, 1))
        return jnp.where(topm, 1.0,
                         jnp.where(pick, amp, 0.0)).astype(jnp.float32)


class MVS(GBDT):
    """Minimal-variance sampling — the fork's addition (``mvs.hpp:28``):
    per-row score sqrt((sum_k |g*h|)^2 + var_weight), adaptive threshold
    mu solving  sum_i min(1, s_i/mu) = bagging_fraction * n
    (``CalculateThreshold``, ``mvs.hpp:91``); rows below mu are kept
    with probability s/mu and importance-weighted by mu/s."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        Log.info("Using MVS")

    @staticmethod
    def _threshold_device(s, target: float):
        """Smallest mu with sum(min(1, s/mu)) <= target (expected
        sample size).  Closed form over the descending order statistic
        (equivalent to the reference's recursive partition), as device
        ops: one sort + one cumsum."""
        import jax.numpy as jnp
        n = s.shape[0]
        s_desc = -jnp.sort(-s)
        suffix = jnp.cumsum(s_desc[::-1])[::-1]  # suffix[i] = sum(s[i:])
        idx = jnp.arange(n, dtype=jnp.float32)
        est = idx + suffix / jnp.maximum(s_desc, 1e-35)
        # est is nondecreasing; first position whose estimate exceeds
        # the target brackets the threshold
        over = est > target
        i = jnp.argmax(over)
        mu_in = suffix[i] / jnp.maximum(target - i.astype(jnp.float32),
                                        1e-10)
        return jnp.where(jnp.any(over), mu_in, s_desc[-1])

    def _bagging_mask(self, grad=None, hess=None):
        if grad is None or self.config.bagging_fraction >= 1.0:
            return None
        return self._mvs_mask(self.iter, grad, hess)

    def _fused_mask_fn(self):
        """MVS inside the fused super-step: pure function of the
        iteration's gradients + the global-iteration PRNG fold."""
        if self.config.bagging_fraction >= 1.0:
            return None
        return lambda it, prev, grad, hess: self._mvs_mask(it, grad,
                                                           hess)

    def _mvs_mask(self, it, grad, hess):
        """One jitted program from both call sites — see
        :meth:`GOSS._goss_mask`."""
        import jax
        if getattr(self, "_trace_raw", False):
            # battery trace: see GOSS._goss_mask
            return self._mvs_mask_impl(it, grad, hess)
        if getattr(self, "_mvs_mask_jit", None) is None:
            self._mvs_mask_jit = jax.jit(self._mvs_mask_impl)
        return self._mvs_mask_jit(it, grad, hess)

    def _mvs_mask_impl(self, it, grad, hess):
        import jax
        import jax.numpy as jnp
        cfg = self.config
        n = self.num_data
        gh = jnp.sum(jnp.abs(grad * hess), axis=0)[:n]
        s = jnp.sqrt(gh * gh + jnp.float32(cfg.var_weight))
        mu = self._threshold_device(s, cfg.bagging_fraction * n)
        key = jax.random.fold_in(self._bag_key, it)
        prob = jnp.minimum(s / jnp.maximum(mu, 1e-35), 1.0)
        keep = jax.random.uniform(key, (n,)) < prob
        return jnp.where(keep, 1.0 / jnp.maximum(prob, 1e-35),
                         0.0).astype(jnp.float32)


class DART(GBDT):
    """Dropouts meet MART (``dart.hpp:17``): per iteration, drop a
    random subset of past trees from the training score, fit the new
    tree against the reduced score, then renormalize the new and
    dropped trees by k/(k+1) (``DroppingTrees:91``, ``Normalize:59``;
    xgboost mode uses k/(k+lr))."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._track_train_leaf = True
        self._pipeline_enabled = False  # drops need the host tree
        self._superstep_enabled = False  # per-iter drops/renormalize
        self._rng_drop = np.random.RandomState(
            self.config.drop_seed & 0x7FFFFFFF)
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0
        self._drop_index: List[int] = []
        Log.info("Using DART")

    def init_from_model(self, models, raw) -> None:
        super().init_from_model(models, raw)
        # seed per-iteration drop weights: each seeded tree's stored
        # cumulative shrinkage is the best available estimate of its
        # normalized DART weight
        K = self.num_tree_per_iteration
        self.tree_weight = [float(self.models[i * K].shrinkage)
                            for i in range(self.iter)]
        self.sum_weight = float(sum(self.tree_weight))

    # -- checkpoint/resume: drop RNG + per-tree weight state ----------
    def _extra_ckpt_state(self):
        return {"rng_drop": self._rng_drop.get_state(),
                "tree_weight": list(self.tree_weight),
                "sum_weight": float(self.sum_weight)}

    def _restore_extra_ckpt_state(self, extra, raw) -> None:
        if "rng_drop" in extra:
            self._rng_drop.set_state(extra["rng_drop"])
        self.tree_weight = [float(w)
                            for w in extra.get("tree_weight", [])]
        self.sum_weight = float(extra.get("sum_weight", 0.0))
        self._drop_index = []
        self._dart_undo = None

    # -- per-tree train contribution from the stored leaf assignment --
    def _train_contrib(self, model_idx: int):
        import jax.numpy as jnp
        from ..ops.lookup import take_small
        tree = self.models[model_idx]
        la = self._train_leaf_idx[model_idx]
        if la is None:
            return jnp.float32(tree.leaf_value[0])
        # pad the table to a STABLE shape — the lookup kernel's
        # unrolled select-chain compiles per table length; seeded trees
        # from a donor model may exceed the current num_leaves
        L = max(self.config.num_leaves, tree.num_leaves)
        vals = np.zeros(L, np.float32)
        vals[:tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
        return take_small(jnp.asarray(vals), jnp.asarray(la, jnp.int32))

    def _select_drops(self) -> None:
        cfg = self.config
        self._drop_index = []
        if self._rng_drop.random_sample() < cfg.skip_drop or self.iter == 0:
            pass
        elif cfg.uniform_drop:
            rate = cfg.drop_rate
            if cfg.max_drop > 0:
                rate = min(rate, cfg.max_drop / float(self.iter))
            for i in range(self.iter):
                if self._rng_drop.random_sample() < rate:
                    self._drop_index.append(i)
                    if len(self._drop_index) >= cfg.max_drop > 0:
                        break
        else:
            inv_avg = len(self.tree_weight) / max(self.sum_weight, _KEPS)
            rate = cfg.drop_rate
            if cfg.max_drop > 0:
                rate = min(rate, cfg.max_drop * inv_avg /
                           max(self.sum_weight, _KEPS))
            for i in range(self.iter):
                if self._rng_drop.random_sample() < \
                        rate * self.tree_weight[i] * inv_avg:
                    self._drop_index.append(i)
                    if len(self._drop_index) >= cfg.max_drop > 0:
                        break
        k = float(len(self._drop_index))
        lr = self.config.learning_rate
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = lr / (1.0 + k)
        else:
            self.shrinkage_rate = lr if not self._drop_index else \
                lr / (lr + k)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        # snapshot BEFORE drops so rollback restores a consistent state
        pre_score = self._score
        pre_valid = [vs.score.copy() for vs in self.valid_sets]
        pre_weights = (list(self.tree_weight), self.sum_weight)
        self._select_drops()
        K = self.num_tree_per_iteration
        # remove dropped trees from the training score so gradients see
        # the reduced ensemble
        for i in self._drop_index:
            for k in range(K):
                self._score = self._score.at[k].add(
                    -self._train_contrib(i * K + k))
        stop = super().train_one_iter(grad, hess)
        if stop:
            # no tree was added: restore the dropped contributions so
            # the score matches the (unchanged) model, and invalidate
            # the undo snapshot (it describes an older iteration)
            for i in self._drop_index:
                for k in range(K):
                    self._score = self._score.at[k].add(
                        self._train_contrib(i * K + k))
            self._drop_index = []
            self._dart_undo = None
            return stop
        scale = self._normalize()
        if not self.config.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        self._dart_undo = (pre_score, pre_valid, pre_weights,
                           list(self._drop_index), scale)
        return False

    def rollback_one_iter(self) -> None:
        """Undo the last DART iteration: restore pre-drop scores, unscale
        the renormalized dropped trees, and pop the new tree."""
        if self.iter <= 0 or getattr(self, "_dart_undo", None) is None:
            return
        pre_score, pre_valid, (tw, sw), dropped, scale = self._dart_undo
        K = self.num_tree_per_iteration
        # pop-then-retrain aliases the count-keyed flattened-predictor
        # cache (and non-empty drops additionally unscale in place)
        self._invalidate_predictor()
        for i in dropped:
            for k in range(K):
                self.models[i * K + k].apply_shrinkage(1.0 / scale)
        self._score = pre_score
        for vs, snap in zip(self.valid_sets, pre_valid):
            vs.score = snap
        self.tree_weight, self.sum_weight = tw, sw
        for _ in range(K):
            self.models.pop()
            if self._train_leaf_idx:
                self._train_leaf_idx.pop()
            for vs in self.valid_sets:
                if vs.leaf_idx_per_tree:
                    vs.leaf_idx_per_tree.pop()
        self.iter -= 1
        self._dart_undo = None

    def _normalize(self) -> float:
        k = float(len(self._drop_index))
        if k == 0:
            return 1.0
        # renormalization rescales EXISTING trees' leaf values in
        # place — the flattened inference tables must be rebuilt
        self._invalidate_predictor()
        cfg = self.config
        lr = cfg.learning_rate
        scale = k / (k + 1.0) if not cfg.xgboost_dart_mode else \
            k / (k + lr)
        K = self.num_tree_per_iteration
        for i in self._drop_index:
            for kk in range(K):
                mi = i * K + kk
                tree = self.models[mi]
                tree.apply_shrinkage(scale)
                # train score: net change is -(1-scale) x original
                self._score = self._score.at[kk].add(
                    self._train_contrib(mi))
                # valid scores: subtract the same (1-scale) slice via
                # the stored per-tree leaf tables (a numpy lookup, not
                # an O(rows x depth) host tree walk per drop)
                if self.valid_sets:
                    factor = (1.0 - scale) / scale
                    for vs in self.valid_sets:
                        la = vs.leaf_idx_per_tree[mi] \
                            if mi < len(vs.leaf_idx_per_tree) else None
                        if la is None:
                            contrib = tree.leaf_value[0] \
                                if tree.num_leaves <= 1 else \
                                tree.predict(vs.raw)
                        else:
                            contrib = tree.leaf_value[
                                la.astype(np.int32)]
                        vs.score[kk] -= contrib * factor
            if not cfg.uniform_drop:
                unit = (k + 1.0) if not cfg.xgboost_dart_mode else (k + lr)
                self.sum_weight -= self.tree_weight[i] / unit
                self.tree_weight[i] *= scale
        return scale


class RF(GBDT):
    """Random forest (``rf.hpp:18``): unit shrinkage, mandatory
    bagging, gradients computed ONCE from the constant init score, and
    the model score maintained as the AVERAGE of tree outputs."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        cfg = self.config
        if not (cfg.bagging_freq > 0 and 0 < cfg.bagging_fraction < 1):
            Log.fatal("random forest requires bagging "
                      "(bagging_freq > 0, 0 < bagging_fraction < 1)")
        self.average_output = True
        self._pipeline_enabled = False  # averaged-score updates
        self._superstep_enabled = False  # averaged-score updates
        self.shrinkage_rate = 1.0
        if self.objective is None:
            Log.fatal("rf does not support a custom objective")
        if self.train_set.metadata.init_score is not None:
            # rf.hpp:38 — the averaged-score update is incompatible
            # with a per-row initial score
            Log.fatal("cannot use initial score for random forest")
        Log.info("Using RF")
        K = self.num_tree_per_iteration
        self._init_scores = [0.0] * K
        if self.config.boost_from_average and self.objective is not None:
            for k in range(K):
                self._init_scores[k] = self.objective.boost_from_score(k)
        # fixed gradients from the constant init score (RF::Boosting)
        import jax.numpy as jnp
        base = jnp.asarray(
            np.repeat(np.asarray(self._init_scores, np.float32)[:, None],
                      self.num_data, axis=1))
        g, h = self.objective.get_gradients(base)
        self._rf_grad = jnp.atleast_2d(g)
        self._rf_hess = jnp.atleast_2d(h)

    def _train_one_iter_impl(self, grad=None, hess=None) -> bool:
        # overriding the IMPL keeps the base train_one_iter's telemetry
        # wrapper (per-iteration run records) around RF iterations too
        import jax.numpy as jnp
        if grad is not None:
            Log.fatal("rf does not support a custom objective")
        self._prev_score = self._score
        self._prev_valid_scores = [vs.score.copy() for vs in self.valid_sets]
        bag = self._bagging_mask()
        K = self.num_tree_per_iteration
        m = float(self.iter)
        for k in range(K):
            # average-maintaining update: score <- (score*m + tree)/(m+1)
            self._score = self._score.at[k].multiply(m)
            for vs in self.valid_sets:
                vs.score[k] *= m
            tree = self._train_one_tree(self._rf_grad[k], self._rf_hess[k],
                                        bag, self._init_scores[k])
            # the per-tree bias is inside the tree but excluded from the
            # incremental score update; add it so the average is exact
            if abs(self._init_scores[k]) > _KEPS and tree.num_leaves > 1:
                self._score = self._score.at[k].add(self._init_scores[k])
                for vs in self.valid_sets:
                    vs.score[k] += self._init_scores[k]
            self._score = self._score.at[k].multiply(1.0 / (m + 1.0))
            for vs in self.valid_sets:
                vs.score[k] /= (m + 1.0)
            self.models.append(tree)
        self.iter += 1
        return False


_BOOSTING_TYPES = {
    "gbdt": GBDT, "gbrt": GBDT,
    "dart": DART,
    "goss": GOSS,
    "rf": RF, "random_forest": RF,
    "mvs": MVS,
}


def create_boosting(config: Config, train_set: TpuDataset,
                    objective: Optional[Objective],
                    metrics: Sequence[Metric] = (), mesh=None) -> GBDT:
    """``Boosting::CreateBoosting`` (``boosting.cpp:33-58``)."""
    cls = _BOOSTING_TYPES.get(config.boosting)
    if cls is None:
        Log.fatal("unknown boosting type %s", config.boosting)
    return cls(config, train_set, objective, metrics, mesh=mesh)
