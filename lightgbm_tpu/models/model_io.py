"""Model text / JSON serialization.

Capability parity with ``src/boosting/gbdt_model_text.cpp``: versioned
text model (``SaveModelToString:244``), load (``LoadModelFromString:343``),
JSON dump (``DumpModel:15``), and feature importance
(``FeatureImportance:513``).  The format matches the reference's v2 text
layout so models can be exchanged with the reference implementation.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ..utils.log import Log
from .tree import Tree

_EOT = "end of trees"


def write_model_file(filename: str, text: str) -> None:
    """Write model text ATOMICALLY: temp + fsync + rename via the
    checkpoint writer (``ckpt/atomic.py``), so a crash mid-save can
    never leave a truncated model file — the reader sees the complete
    old model or the complete new one.  Remote (hdfs://) targets keep
    the upload-on-close path of ``utils/file_io.py`` (their atomicity
    is the filesystem's contract, not ours)."""
    from ..utils.file_io import is_remote, open_output
    filename = str(filename)
    if is_remote(filename):
        with open_output(filename) as f:
            f.write(text)
        return
    from ..ckpt.atomic import atomic_write_text
    atomic_write_text(filename, text)


def save_model_to_string(models: List[Tree], *, num_class: int,
                         num_tree_per_iteration: int, label_index: int,
                         max_feature_idx: int, objective_str: str,
                         feature_names: List[str],
                         feature_infos: List[str],
                         num_iteration: int = -1,
                         parameters: str = "",
                         average_output: bool = False) -> str:
    k = num_tree_per_iteration
    n_trees = len(models)
    if num_iteration is not None and num_iteration > 0:
        n_trees = min(n_trees, num_iteration * k)
    tree_strs = [models[i].to_string(i) for i in range(n_trees)]
    out = ["tree", "version=v2",
           f"num_class={num_class}",
           f"num_tree_per_iteration={k}",
           f"label_index={label_index}",
           f"max_feature_idx={max_feature_idx}",
           f"objective={objective_str}"]
    if average_output:
        out.append("average_output")  # RF marker (gbdt_model_text.cpp:258)
    out += ["feature_names=" + " ".join(feature_names),
            "feature_infos=" + " ".join(feature_infos),
            "tree_sizes=" + " ".join(str(len(s) + 1) for s in tree_strs),
            ""]
    for s in tree_strs:
        out.append(s)
    out.append(_EOT + "\n")
    imp = feature_importance(models[:n_trees], "split")
    pairs = sorted([(feature_names[i], int(v)) for i, v in enumerate(imp)
                    if i < len(feature_names) and v > 0],
                   key=lambda x: -x[1])
    out.append("feature importances:")
    out += [f"{n}={v}" for n, v in pairs]
    if parameters:
        out.append("\nparameters:")
        out.append(parameters)
        out.append("end of parameters")
    return "\n".join(out) + "\n"


def load_model_from_string(text: str) -> Dict:
    """Parse a model file into {models, header fields}."""
    if not text.startswith("tree"):
        Log.fatal("model text does not start with 'tree' header")
    header, _, rest = text.partition("\nTree=")
    kv: Dict[str, str] = {}
    for line in header.splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            kv[k] = v
    trees_text = rest.split(_EOT)[0] if rest else ""
    models = []
    for block in trees_text.split("\nTree="):
        block = block.strip()
        if not block:
            continue
        models.append(Tree.from_string("Tree=" + block))
    return {
        "models": models,
        "num_class": int(kv.get("num_class", "1")),
        "num_tree_per_iteration": int(kv.get("num_tree_per_iteration", "1")),
        "label_index": int(kv.get("label_index", "0")),
        "max_feature_idx": int(kv.get("max_feature_idx", "0")),
        "objective": kv.get("objective", "regression"),
        "feature_names": kv.get("feature_names", "").split(),
        "feature_infos": kv.get("feature_infos", "").split(),
        "average_output": any(line.strip() == "average_output"
                              for line in header.splitlines()),
    }


def dump_model_json(models: List[Tree], *, num_class: int,
                    num_tree_per_iteration: int, label_index: int,
                    max_feature_idx: int, objective_str: str,
                    feature_names: List[str],
                    num_iteration: int = -1) -> Dict:
    k = num_tree_per_iteration
    n_trees = len(models)
    if num_iteration is not None and num_iteration > 0:
        n_trees = min(n_trees, num_iteration * k)
    return {
        "name": "tree",
        "version": "v2",
        "num_class": num_class,
        "num_tree_per_iteration": k,
        "label_index": label_index,
        "max_feature_idx": max_feature_idx,
        "objective": objective_str,
        "feature_names": feature_names,
        "tree_info": [models[i].to_json(i) for i in range(n_trees)],
    }


def feature_importance(models: List[Tree], importance_type: str = "split",
                       num_features: Optional[int] = None) -> np.ndarray:
    """split count or total gain per feature
    (``GBDT::FeatureImportance``)."""
    if num_features is None:
        num_features = 0
        for t in models:
            if t.num_leaves > 1:
                num_features = max(num_features,
                                   int(t.split_feature[:t.num_leaves - 1]
                                       .max()) + 1)
    imp = np.zeros(num_features, dtype=np.float64)
    for t in models:
        n = t.num_leaves - 1
        for i in range(n):
            f = t.split_feature[i]
            if importance_type == "split":
                imp[f] += 1
            else:
                imp[f] += t.split_gain[i]
    return imp
