"""lightgbm_tpu — a TPU-native gradient boosting framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of
LightGBM v2.2.3 (reference: ``/root/reference``): histogram-based leaf-wise
GBDT/DART/GOSS/RF/MVS boosting, the full objective/metric zoo, quantile
binning with categorical and missing-value handling, distributed
data/feature/voting-parallel learning over a ``jax.sharding.Mesh``, and a
Python ``train/cv/Dataset/Booster`` + sklearn + CLI API.
"""
from .config import Config
from .utils.log import Log, LightGBMError

__version__ = "0.1.0"

__all__ = ["Config", "Log", "LightGBMError", "__version__"]


def __getattr__(name):
    # heavier API surface is imported lazily so `import lightgbm_tpu`
    # stays cheap and jax-free until needed
    if name in ("Dataset", "Booster"):
        from . import basic
        return getattr(basic, name)
    if name in ("train", "cv", "CVBooster"):
        from . import engine
        return getattr(engine, name)
    if name in ("LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"):
        from . import sklearn
        return getattr(sklearn, name)
    if name in ("early_stopping", "print_evaluation", "record_evaluation",
                "record_telemetry", "reset_parameter"):
        from . import callback
        return getattr(callback, name)
    if name in ("plot_importance", "plot_metric", "plot_tree",
                "create_tree_digraph"):
        from . import plotting
        return getattr(plotting, name)
    raise AttributeError(f"module 'lightgbm_tpu' has no attribute {name!r}")
