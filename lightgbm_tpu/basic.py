"""Public ``Dataset`` / ``Booster`` API.

Capability parity with ``python-package/lightgbm/basic.py``: lazy
``Dataset`` construction with reference alignment for validation sets,
pandas and categorical handling, field get/set; ``Booster`` with
train/eval/predict (raw / leaf index / SHAP contrib), model
save/load/dump and continue-training.

TPU-first: there is no ctypes bridge — the "native" layer is the JAX
device program (``ops/``), and the Dataset pushes one dense binned
matrix to HBM instead of per-feature Bin columns.
"""
from __future__ import annotations

import io
import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config
from .io.binning import BIN_CATEGORICAL
from .io.dataset import Metadata, TpuDataset
from .io.parser import load_float_file, load_query_file, parse_file_full
from .metrics import Metric, create_metrics, default_metric_for
from .models.gbdt import GBDT
from .models.boosting import create_boosting
from .models import model_io
from .models.tree import Tree
from .objectives import create_objective
from .utils.log import Log

__all__ = ["Dataset", "Booster"]


def _resolve_cat_indices(spec, names):
    """Name-or-index categorical spec -> column indices (shared by the
    file / sparse / matrix construction branches)."""
    cat_idx = []
    for c in spec:
        if isinstance(c, str):
            if not names or c not in names:
                Log.fatal("categorical feature name %s not found", c)
            cat_idx.append(names.index(c))
        else:
            cat_idx.append(int(c))
    return cat_idx


def _to_matrix(data, feature_name="auto", categorical_feature="auto"):
    """Normalize input data to (matrix, feature_names, categorical_idx)."""
    cat_idx: List[int] = []
    names = None
    if hasattr(data, "dtypes") and hasattr(data, "columns"):  # pandas
        import pandas as pd
        df = data.copy()
        names = [str(c) for c in df.columns]
        for i, col in enumerate(df.columns):
            if str(df[col].dtype) == "category":
                df[col] = df[col].cat.codes
                cat_idx.append(i)
            elif df[col].dtype == object:
                Log.fatal("pandas object column %s is not supported; "
                          "use category dtype or numeric", col)
        mat = df.values
        if mat.dtype != np.float32:
            mat = mat.astype(np.float64)
    elif hasattr(data, "toarray"):
        # scipy CSR/CSC/COO: densify (the TPU layout is dense; EFB
        # re-narrows exclusive sparse columns downstream), matching the
        # C API's CSR/CSC construction surface (c_api.h:48-232)
        mat = np.asarray(data.toarray())
        if mat.dtype != np.float32:
            mat = mat.astype(np.float64)
    else:
        # float32 is kept narrow (the reference's python binding casts
        # everything to float32, basic.py:270); other dtypes go f64
        mat = np.asarray(data)
        if mat.dtype != np.float32:
            mat = np.asarray(mat, dtype=np.float64)
        if mat.ndim == 1:
            mat = mat.reshape(-1, 1)
    if feature_name != "auto" and feature_name is not None:
        names = list(feature_name)
    if categorical_feature != "auto" and categorical_feature is not None:
        cat_idx = _resolve_cat_indices(categorical_feature, names)
    return mat, names, cat_idx


class Dataset:
    """Training/validation data container (lazy construction like the
    reference: binning happens at first use, and validation sets align
    their bins with their ``reference`` train set)."""

    def __init__(self, data, label=None, reference: "Dataset" = None,
                 weight=None, group=None, init_score=None,
                 feature_name="auto", categorical_feature="auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = False, silent: bool = False):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params) if params else {}
        self.free_raw_data = free_raw_data
        self._constructed: Optional[TpuDataset] = None
        self.raw_mat: Optional[np.ndarray] = None
        self.used_indices: Optional[np.ndarray] = None
        # streaming construction (C API PushRows / CreateByReference):
        # a pre-allocated (num_total_row, ncol) buffer filled in chunks;
        # when full it becomes self.data
        self._stream: Optional[Dict[str, Any]] = None
        # bin mappers fixed ahead of data (CreateFromSampledColumn)
        self._preset_mappers = None

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._constructed is not None:
            return self
        cfg = Config(self.params)
        label, weight, group = self.label, self.weight, self.group
        if self.categorical_feature in ("auto", None) and \
                getattr(cfg, "categorical_feature", ""):
            # params/conf-file spec (``categorical_feature=6,7,8`` or
            # ``name:c1,c2`` — io/config.h categorical_feature): the
            # reference honors it for FILE data too, so resolve it
            # before the data-source branches
            spec = cfg.categorical_feature
            if isinstance(spec, str):
                spec = spec[5:] if spec.startswith("name:") else spec
                spec = [s.strip() for s in spec.split(",") if s.strip()]
                spec = [int(s) if s.lstrip("+-").isdigit() else s
                        for s in spec]
            self.categorical_feature = list(spec)

        stream_ok = False
        if getattr(cfg, "stream_ingest", False) and \
                self.reference is None and self.used_indices is None:
            if isinstance(self.data, (str, os.PathLike)):
                # only the streamed loader's own formats: a directory
                # of npz shards or an .X.npy mmap pair.  CSV/LibSVM/
                # binary-dataset paths fall through to the normal
                # loader rather than failing inside the stream path
                path = str(self.data)
                stem = path[:-len(".X.npy")] \
                    if path.endswith(".X.npy") else path
                stream_ok = os.path.isdir(path) or \
                    os.path.exists(stem + ".X.npy")
            else:
                stream_ok = self.data is not None and \
                    not hasattr(self.data, "tocsc")
            if not stream_ok:
                Log.warning("stream_ingest=true ignored: %r is not a "
                            "streamable source (ndarray, <stem>.X.npy "
                            "mmap pair, or npz shard directory); "
                            "using the in-memory loader",
                            type(self.data).__name__
                            if not isinstance(self.data,
                                              (str, os.PathLike))
                            else str(self.data))
        if stream_ok:
            # out-of-core streamed ingest (docs/Streaming.md): the raw
            # matrix is binned chunk-by-chunk into the crash-safe
            # mmap cache and never fully materializes on the host;
            # the trained model is byte-identical to this same data
            # through the in-memory path.  Validation sets (reference
            # is set) stay on the in-memory alignment path.
            from .io import stream as stream_mod
            self._constructed = stream_mod.ingest_dataset(
                self.data, label=label, weight=weight, group=group,
                init_score=self.init_score, config=cfg,
                feature_name=self.feature_name,
                categorical_feature=self.categorical_feature)
            self.raw_mat = None
            if self.feature_name == "auto":
                self.feature_name = self._constructed.feature_names
            return self
        if isinstance(self.data, (str, os.PathLike)):
            from .utils.file_io import is_remote, localize
            remote = is_remote(str(self.data))
            path = localize(str(self.data))
            if TpuDataset.is_binary_file(path):
                self._constructed = TpuDataset.load_binary(path)
                self.raw_mat = None
                return self
            mat, y, names, w, g = parse_file_full(
                path, header=cfg.header, label_column=cfg.label_column,
                ignore_columns=cfg.ignore_column,
                weight_column=cfg.weight_column,
                group_column=cfg.group_column)
            label = y if label is None else label
            if w is not None and weight is None:
                weight = w
            if g is not None and group is None:
                group = g
            # sidecar files ride next to the data; remote datasets skip
            # the probe (a missing remote sidecar is indistinguishable
            # from a fetch failure)
            sw = None if remote else load_float_file(path + ".weight")
            if sw is not None and weight is None:
                weight = sw
            sq = None if remote else load_query_file(path + ".query")
            if sq is not None and group is None:
                group = sq
            # initscore_filename overrides the ``<data>.init`` sidecar
            # for the TRAINING set only; valid sets get theirs from
            # valid_data_initscores (wired in the CLI)
            init_path = ""
            if self.reference is None:
                init_path = getattr(cfg, "initscore_filename", "")
            si = load_float_file(init_path) if init_path else \
                (None if remote else load_float_file(path + ".init"))
            if si is not None and self.init_score is None:
                self.init_score = si
            cat_idx = []
            if self.categorical_feature not in ("auto", None):
                cat_idx = _resolve_cat_indices(self.categorical_feature,
                                               names)
            if self.feature_name == "auto":
                self.feature_name = names
        elif hasattr(self.data, "tocsc") and self.used_indices is None:
            # scipy sparse: chunked CSC binning, no f64 densify (the
            # round-2 verdict's Bosch/Epsilon-scale memory hazard)
            names = self.feature_name \
                if self.feature_name not in ("auto", None) else None
            cat_idx = []
            if self.categorical_feature not in ("auto", None):
                cat_idx = _resolve_cat_indices(self.categorical_feature,
                                               names)
            mappers = None
            if self.reference is not None:
                self.reference.construct()
                mappers = self.reference._constructed.mappers
            self._constructed = TpuDataset.from_sparse(
                self.data, label, cfg, weight=weight, group=group,
                init_score=self.init_score, feature_names=names,
                categorical_features=cat_idx, mappers=mappers)
            # raw stays SPARSE; dense consumers densify on demand
            self.raw_mat = None if self.free_raw_data else self.data
            return self
        else:
            mat, names, cat_idx = _to_matrix(self.data, self.feature_name,
                                             self.categorical_feature)
            if self.feature_name == "auto":
                self.feature_name = names

        if self.used_indices is not None:
            mat = mat[self.used_indices]
            label = None if label is None else \
                np.asarray(label)[self.used_indices]
            weight = None if weight is None else \
                np.asarray(weight)[self.used_indices]
            # group subsetting handled by caller providing group directly

        mappers = self._preset_mappers
        if self.reference is not None:
            self.reference.construct()
            mappers = self.reference._constructed.mappers
        self._constructed = TpuDataset.from_raw(
            mat, label, cfg, weight=weight, group=group,
            init_score=self.init_score,
            feature_names=self.feature_name if self.feature_name else None,
            categorical_features=cat_idx, mappers=mappers)
        self.raw_mat = None if self.free_raw_data else mat
        return self

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params)

    def subset(self, used_indices, params=None) -> "Dataset":
        # bins must MATCH the parent (the reference's CopySubrow shares
        # the parent's mappers): a root dataset becomes its subset's
        # reference; a valid set's subset keeps the original reference
        ds = Dataset(self.data, label=self.label,
                     reference=self.reference if self.reference is not None
                     else self,
                     weight=self.weight, group=None,
                     feature_name=self.feature_name,
                     categorical_feature=self.categorical_feature,
                     params=params or self.params)
        ds.used_indices = np.asarray(used_indices)
        return ds

    def save_binary(self, filename: str) -> "Dataset":
        self.construct()
        from .utils.file_io import is_remote
        filename = str(filename)
        if is_remote(filename):
            import shutil
            import tempfile
            from .utils.file_io import open_output
            with tempfile.NamedTemporaryFile(suffix=".bin") as tmp:
                self._constructed.save_binary(tmp.name)
                with open(tmp.name, "rb") as src, \
                        open_output(filename, "wb") as dst:
                    shutil.copyfileobj(src, dst)
        else:
            self._constructed.save_binary(filename)
        return self

    # ---- field access -------------------------------------------------
    def num_data(self) -> int:
        self.construct()
        return self._constructed.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._constructed.num_total_features

    def get_label(self):
        self.construct()
        return np.asarray(self._constructed.metadata.label)

    def get_weight(self):
        self.construct()
        return self._constructed.metadata.weight

    def get_group(self):
        self.construct()
        qb = self._constructed.metadata.query_boundaries
        return None if qb is None else np.diff(qb)

    def get_init_score(self):
        self.construct()
        return self._constructed.metadata.init_score

    def set_label(self, label):
        self.label = label
        if self._constructed is not None:
            self._constructed.metadata.set_label(label)
        return self

    def set_weight(self, weight):
        self.weight = weight
        if self._constructed is not None:
            self._constructed.metadata.set_weight(weight)
        return self

    def set_group(self, group):
        self.group = group
        if self._constructed is not None:
            self._constructed.metadata.set_query(group)
        return self

    def set_init_score(self, init_score):
        self.init_score = init_score
        if self._constructed is not None:
            self._constructed.metadata.set_init_score(init_score)
        return self

    # ---- streaming construction (C API surface) ----------------------
    def begin_streaming(self, num_total_row: int, ncol: int) -> None:
        """Pre-allocate the push buffer (``LGBM_DatasetCreateByReference``
        + ``LGBM_DatasetPushRows``, ``c_api.h:81-125``)."""
        self._stream = {
            "buf": np.zeros((int(num_total_row), int(ncol)), np.float64),
            "total": int(num_total_row),
        }

    def push_rows(self, rows: np.ndarray, start_row: int) -> None:
        if self._stream is None:
            Log.fatal("push_rows on a dataset not created for streaming")
        if self._constructed is not None:
            Log.fatal("push_rows after dataset construction")
        s = self._stream
        rows = np.asarray(rows, np.float64)
        s["buf"][start_row:start_row + rows.shape[0]] = rows
        # the FinishLoad trigger is POSITIONAL (c_api.h:86: "if nrow +
        # start_row == num_total_row, will call dataset->FinishLoad"),
        # so re-pushed/overlapping chunks cannot finalize early
        if start_row + rows.shape[0] >= s["total"]:
            self.data = s["buf"]
            self._stream = None

    def set_feature_names(self, names) -> "Dataset":
        self.feature_name = [str(n) for n in names]
        if self._constructed is not None:
            self._constructed.feature_names = list(self.feature_name)
        return self

    def get_feature_names(self):
        if self._constructed is not None:
            return list(self._constructed.feature_names)
        return list(self.feature_name) if self.feature_name and \
            self.feature_name != "auto" else []

    def update_params(self, params: Dict[str, Any]) -> "Dataset":
        """``LGBM_DatasetUpdateParam`` (``c_api.h:318``): merge params;
        binning-affecting changes only apply before construction."""
        if self._constructed is not None and params:
            Log.warning("dataset is already constructed; updated "
                        "parameters only affect future operations")
        self.params = {**self.params, **(params or {})}
        return self

    def set_field(self, name, data):
        return {"label": self.set_label, "weight": self.set_weight,
                "group": self.set_group,
                "init_score": self.set_init_score}[name](data)

    def get_field(self, name):
        return {"label": self.get_label, "weight": self.get_weight,
                "group": self.get_group,
                "init_score": self.get_init_score}[name]()


class Booster:
    """Trained model handle (``basic.py:1485`` in the reference)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent: bool = False,
                 mesh=None):
        params = dict(params) if params else {}
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._gbdt: Optional[GBDT] = None
        self._loaded: Optional[Dict] = None
        self.train_set = train_set
        self.params = params

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                Log.fatal("train_set must be a Dataset")
            train_set.params = {**train_set.params, **params}
            train_set.construct()
            self.config = Config(params)
            if self.config.objective in ("none", "custom", "null", "na"):
                objective = None  # custom fobj supplies gradients
            else:
                objective = create_objective(self.config.objective,
                                             self.config)
            self._metric_names = self._resolve_metric_names(self.config)
            metrics = create_metrics(self._metric_names, self.config)
            self._gbdt = create_boosting(self.config, train_set._constructed,
                                         objective, metrics, mesh=mesh)
            self._valid_names: List[str] = []
        elif model_file is not None or model_str is not None:
            if model_file is not None:
                from .utils.file_io import localize
                with open(localize(str(model_file))) as f:
                    model_str = f.read()
            self._load_from_string(model_str)
        else:
            Log.fatal("need train_set, model_file or model_str")

    @staticmethod
    def _resolve_metric_names(config) -> List[str]:
        m = config.metric
        if isinstance(m, str):
            names = [t.strip() for t in m.split(",")] if m else []
        else:
            names = list(m or [])
        if not names:
            if config.objective in ("none", "custom", "null", "na"):
                return []
            names = [default_metric_for(config.objective)]
        if any(n.lower() in ("none", "na", "null") for n in names):
            return []
        return names

    # ------------------------------------------------------------------
    def _load_from_string(self, text: str) -> None:
        info = model_io.load_model_from_string(text)
        self._loaded = info
        obj_str = info["objective"].split()
        cfg_params: Dict[str, Any] = {"objective": obj_str[0] or "regression"}
        for tok in obj_str[1:]:
            if ":" in tok:
                k, v = tok.split(":", 1)
                cfg_params[k] = v
        cfg_params["num_class"] = info["num_class"]
        self.config = Config(cfg_params)
        self._gbdt = GBDT.__new__(GBDT)
        g = self._gbdt
        g.config = self.config
        g.train_set = None
        g.models = info["models"]
        g.num_class = info["num_class"]
        g.num_tree_per_iteration = info["num_tree_per_iteration"]
        g.metrics = []
        g.valid_sets = []
        g.iter = len(info["models"]) // max(info["num_tree_per_iteration"], 1)
        g.average_output = bool(info.get("average_output"))
        g.objective = (create_objective(self.config.objective, self.config)
                       if obj_str and obj_str[0] else None)
        self._feature_names = info["feature_names"]
        self._feature_infos = info["feature_infos"]
        self._max_feature_idx = info["max_feature_idx"]
        self._valid_names = []

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.reference = data.reference or self.train_set
        data.construct()
        if not self.train_set._constructed.check_align(data._constructed):
            Log.fatal("validation set %s bins are not aligned with the "
                      "training set (construct it with reference=train_set)",
                      name)
        if data.raw_mat is None:
            Log.fatal("validation set %s needs raw data for evaluation "
                      "(free_raw_data=False)", name)
        self._gbdt.add_valid(name, data.raw_mat, data._constructed.metadata,
                             binned=data._constructed)
        self._valid_names.append(name)
        # kept for re-registration across reset_training_data /
        # reset_parameter (the reference keeps valid sets registered)
        self._valid_pairs = getattr(self, "_valid_pairs", [])
        self._valid_pairs.append((data, name))
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; returns True if training should stop."""
        if train_set is not None and train_set is not self.train_set:
            self.reset_training_data(train_set)
        if fobj is None:
            return self._gbdt.train_one_iter()
        score = self._gbdt.train_score[0]
        grad, hess = fobj(score.astype(np.float64), self.train_set)
        return self._gbdt.train_one_iter(np.asarray(grad, np.float32),
                                         np.asarray(hess, np.float32))

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    # ------------------------------------------------------------------
    def _rebuild_gbdt(self, train_set: Dataset) -> None:
        """Recreate the boosting driver on ``train_set`` and replay the
        existing model into it (``GBDT::ResetTrainingData`` /
        ``ResetConfig``, ``include/LightGBM/boosting.h:52-55``)."""
        train_set.params = {**train_set.params, **self.params}
        train_set.construct()
        if train_set.raw_mat is None:
            Log.fatal("resetting training data requires raw data "
                      "(free_raw_data=False)")
        models = self._gbdt.models if self._gbdt is not None else []
        if self.config.objective in ("none", "custom", "null", "na"):
            objective = None
        else:
            objective = create_objective(self.config.objective, self.config)
        self._metric_names = self._resolve_metric_names(self.config)
        metrics = create_metrics(self._metric_names, self.config)
        g = create_boosting(self.config, train_set._constructed,
                            objective, metrics)
        if models:
            g.init_from_model(models, train_set.raw_mat)
        self._gbdt = g
        self.train_set = train_set
        # re-register the validation sets on the fresh driver — the
        # reference's ResetConfig/ResetTrainingData keep them attached
        pairs = getattr(self, "_valid_pairs", [])
        self._valid_names = []
        self._valid_pairs = []
        for data, name in pairs:
            self.add_valid(data, name)

    def reset_training_data(self, train_set: Dataset) -> "Booster":
        """Re-point the booster at a new training set, keeping the
        model (``LGBM_BoosterResetTrainingData``, ``c_api.h:411``)."""
        if not isinstance(train_set, Dataset):
            Log.fatal("train_set must be a Dataset")
        self._rebuild_gbdt(train_set)
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """Update boosting parameters in place
        (``LGBM_BoosterResetParameter``, ``c_api.h:420``)."""
        self.params = {**self.params, **params}
        self.config = Config(self.params)
        if self.train_set is not None:
            self._rebuild_gbdt(self.train_set)
        return self

    def merge(self, other: "Booster") -> "Booster":
        """Merge ``other``'s trees in front of this booster's
        (``LGBM_BoosterMerge``, ``c_api.h:393``)."""
        self._gbdt.merge_from(other._gbdt)
        return self

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        self._gbdt.shuffle_models(start_iteration, end_iteration)
        return self

    def refit(self, data, label, weight=None,
              decay_rate: float = 0.9) -> "Booster":
        """Refit the trees' leaf values to new data in place
        (``GBDT::RefitTree``, ``gbdt.cpp:265``)."""
        mat, _, _ = _to_matrix(data)
        self._gbdt.refit(mat, label, weight=weight, decay_rate=decay_rate)
        return self

    def current_iteration(self) -> int:
        return self._gbdt.iter

    def num_trees(self) -> int:
        return len(self._gbdt.models)

    # ------------------------------------------------------------------
    def eval_set(self):
        return self._gbdt.eval_set()

    def eval_valid(self):
        return [r for r in self._gbdt.eval_set() if r[0] != "training"]

    def eval_train(self):
        return [r for r in self._gbdt.eval_set() if r[0] == "training"]

    # ------------------------------------------------------------------
    def predict(self, data, num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        if isinstance(data, Dataset):
            Log.fatal("predict() takes a raw matrix, not a Dataset")
        if isinstance(data, (str, os.PathLike)):
            from .io.parser import parse_file
            data, _, _ = parse_file(str(data), header=False)
        mat, _, _ = _to_matrix(data)
        # only num_iteration=None defaults to best_iteration; an explicit
        # -1/0 means the full ensemble (reference basic.py semantics)
        if num_iteration is None:
            ni = self.best_iteration if self.best_iteration > 0 else -1
        else:
            ni = num_iteration
        # per-call inference-engine overrides (no shared-config
        # mutation: concurrent predicts on one booster stay safe)
        eng = {k: kwargs[k] for k in ("predict_engine",
                                      "predict_chunk_rows")
               if kwargs.get(k) is not None}
        if pred_leaf:
            return self._gbdt.predict_leaf_index(mat, ni, **eng)
        if pred_contrib:
            return self._gbdt.predict_contrib(mat, ni, **eng)
        es = {}
        if kwargs.get("pred_early_stop"):
            es = {"early_stop": True,
                  "early_stop_freq": int(
                      kwargs.get("pred_early_stop_freq", 10)),
                  "early_stop_margin": float(
                      kwargs.get("pred_early_stop_margin", 10.0))}
        if raw_score:
            return self._gbdt.predict_raw(mat, ni, **es, **eng)
        if es:
            raw = self._gbdt.predict_raw(mat, ni, **es, **eng)
            obj = self._gbdt.objective
            return obj.convert_output(raw) if obj is not None else raw
        return self._gbdt.predict(mat, ni, **eng)

    def predict_cache_info(self) -> Dict[str, int]:
        """Inference-engine compile-cache counters (hits / misses /
        evictions / entries / capacity / traces).  The engine is
        process-wide — boosters with identical layouts share compiled
        predictors — so these are process counters, not per-booster;
        the serve layer and tests use them to pin cache behavior."""
        from .ops.predict import get_engine
        return get_engine().cache_info()

    # ------------------------------------------------------------------
    def _objective_string(self) -> str:
        obj = self.config.objective
        if obj in ("none", "custom", "null", "na"):
            return ""
        if obj == "binary":
            return f"binary sigmoid:{self.config.sigmoid:g}"
        if obj in ("multiclass", "multiclassova"):
            return f"{obj} num_class:{self.config.num_class}"
        if obj == "lambdarank":
            return "lambdarank"
        return obj

    def _model_slice(self, start_iteration: int):
        """Trees from ``start_iteration`` on (``c_api.h`` SaveModel /
        DumpModel start_iteration semantics)."""
        g = self._gbdt
        if start_iteration and start_iteration > 0:
            k = max(g.num_tree_per_iteration, 1)
            return g.models[start_iteration * k:]
        return g.models

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        g = self._gbdt
        if g.train_set is not None:
            names = g.train_set.feature_names
            infos = g.train_set.feature_infos()
            max_fi = g.train_set.num_total_features - 1
        else:
            names, infos = self._feature_names, self._feature_infos
            max_fi = self._max_feature_idx
        ni = num_iteration if num_iteration is not None else \
            (self.best_iteration if self.best_iteration > 0 else -1)
        return model_io.save_model_to_string(
            self._model_slice(start_iteration), num_class=g.num_class,
            num_tree_per_iteration=g.num_tree_per_iteration,
            label_index=0, max_feature_idx=max_fi,
            objective_str=self._objective_string(),
            feature_names=names, feature_infos=infos, num_iteration=ni,
            parameters="", average_output=g.average_output)

    def save_model(self, filename: str,
                   num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        # atomic for local paths (ckpt writer: temp + fsync + rename) —
        # a crash mid-save never leaves a truncated model file
        model_io.write_model_file(
            str(filename),
            self.model_to_string(num_iteration, start_iteration))
        return self

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> Dict:
        g = self._gbdt
        if g.train_set is not None:
            names = g.train_set.feature_names
            max_fi = g.train_set.num_total_features - 1
        else:
            names, max_fi = self._feature_names, self._max_feature_idx
        ni = num_iteration if num_iteration is not None else -1
        return model_io.dump_model_json(
            self._model_slice(start_iteration), num_class=g.num_class,
            num_tree_per_iteration=g.num_tree_per_iteration,
            label_index=0, max_feature_idx=max_fi,
            objective_str=self._objective_string(), feature_names=names,
            num_iteration=ni)

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        g = self._gbdt
        nf = (g.train_set.num_total_features if g.train_set is not None
              else self._max_feature_idx + 1)
        models = g.models
        if iteration is not None and iteration > 0:
            models = models[:iteration * g.num_tree_per_iteration]
        return model_io.feature_importance(models, importance_type, nf)

    def feature_name(self) -> List[str]:
        g = self._gbdt
        if g.train_set is not None:
            return list(g.train_set.feature_names)
        return list(self._feature_names)

    def __getstate__(self):
        # picklable via model string (reference Booster pickling support)
        state = {"model_str": self.model_to_string(num_iteration=-1),
                 "best_iteration": self.best_iteration,
                 "best_score": self.best_score,
                 "params": self.params}
        return state

    def __setstate__(self, state):
        self.best_iteration = state["best_iteration"]
        self.best_score = state["best_score"]
        self.params = state["params"]
        self.train_set = None
        self._loaded = None
        self._load_from_string(state["model_str"])
