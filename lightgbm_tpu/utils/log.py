"""Logging with levels and a pluggable callback.

Capability parity with the reference's ``include/LightGBM/utils/log.h``
(levels Debug/Info/Warning/Fatal where Fatal raises, and a user-pluggable
output callback used by the language bindings).
"""
from __future__ import annotations

import sys
from typing import Callable, Optional


class LightGBMError(Exception):
    """Error raised by the framework (Fatal log level)."""


# Numeric levels match the reference semantics: higher = more verbose.
LOG_FATAL = -1
LOG_WARNING = 0
LOG_INFO = 1
LOG_DEBUG = 2


class Log:
    """Static logger. ``Log.fatal`` raises :class:`LightGBMError`."""

    _level: int = LOG_INFO
    _callback: Optional[Callable[[str], None]] = None

    @classmethod
    def reset_level(cls, level: int) -> None:
        cls._level = level

    @classmethod
    def reset_callback(cls, callback: Optional[Callable[[str], None]]) -> None:
        cls._callback = callback

    @classmethod
    def _write(cls, level: int, tag: str, msg: str) -> None:
        if level <= cls._level:
            text = f"[LightGBM-TPU] [{tag}] {msg}"
            if cls._callback is not None:
                cls._callback(text + "\n")
            else:
                print(text, file=sys.stderr, flush=True)

    @classmethod
    def debug(cls, msg: str, *args) -> None:
        cls._write(LOG_DEBUG, "Debug", msg % args if args else msg)

    @classmethod
    def info(cls, msg: str, *args) -> None:
        cls._write(LOG_INFO, "Info", msg % args if args else msg)

    @classmethod
    def warning(cls, msg: str, *args) -> None:
        cls._write(LOG_WARNING, "Warning", msg % args if args else msg)

    @classmethod
    def fatal(cls, msg: str, *args) -> None:
        text = msg % args if args else msg
        cls._write(LOG_FATAL, "Fatal", text)
        raise LightGBMError(text)
