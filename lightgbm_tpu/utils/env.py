"""Environment hygiene helpers for hermetic CPU runs."""
from __future__ import annotations

import os


def force_host_platform_devices(n: int) -> None:
    """Append ``--xla_force_host_platform_device_count=n`` to
    ``XLA_FLAGS`` so the CPU platform exposes ``n`` virtual devices —
    the mesh the sharded tests/benches run on.  Must be called BEFORE
    the first jax import; no-op when the flag is already present (an
    explicit operator choice wins) or ``n <= 1``.  The flag only
    affects the host platform, so it is safe to set even when an
    accelerator backend ends up selected."""
    flags = os.environ.get("XLA_FLAGS", "")
    if n <= 1 or "xla_force_host_platform_device_count" in flags:
        return
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n)}"
    ).strip()


def maybe_init_distributed() -> bool:
    """Env-gated multi-host entry: join the JAX distributed runtime
    when the ``LTPU_COORDINATOR`` env triple is set, no-op otherwise.

    A multi-host launcher exports::

        LTPU_COORDINATOR=host0:12355   # coordinator (process 0)
        LTPU_NUM_PROCESSES=4
        LTPU_PROCESS_ID=<rank>         # or LTPU_MACHINE_RANK

    and every process calls this (the driver does, before building any
    mesh) — afterwards ``jax.devices()`` spans all hosts, so the 1-D
    learners' meshes and the data2d 2-D mesh factor over the GLOBAL
    device set.  Single-host runs (no ``LTPU_COORDINATOR``) return
    False without importing jax.  Idempotent: a runtime already joined
    with the same topology is a no-op; a different topology raises
    (``parallel.distributed.init_distributed``).  Malformed env values
    raise — a silent single-host fallback would train at the wrong
    scale (docs/Distributed.md).
    """
    coordinator = os.environ.get("LTPU_COORDINATOR", "")
    if not coordinator:
        return False
    n = int(os.environ.get("LTPU_NUM_PROCESSES", "1"))
    if n <= 1:
        return False
    rank = os.environ.get("LTPU_PROCESS_ID",
                          os.environ.get("LTPU_MACHINE_RANK"))
    if rank is None:
        raise RuntimeError(
            "LTPU_COORDINATOR is set but neither LTPU_PROCESS_ID nor "
            "LTPU_MACHINE_RANK names this process's rank")
    from ..parallel.distributed import init_distributed
    timeout = os.environ.get("LTPU_INIT_TIMEOUT_S")
    init_distributed(coordinator, n, int(rank),
                     timeout_s=int(timeout) if timeout else None)
    return True


def pallas_interpret_forced() -> bool:
    """True when the ``LTPU_PALLAS_INTERPRET`` env lane is armed: every
    Pallas kernel runs under ``pl.pallas_call(..., interpret=True)``
    AND the driver treats the backend as kernel-capable, so the whole
    kernel tier (histogram passes, routed kernels, the best-split
    scan) executes on a CPU-only host — the tier-1 parity lane for
    code paths that otherwise need a real TPU.  Interpreter-mode wall
    time measures the interpreter, not the kernel; this is a
    correctness lane, never a benchmark."""
    return os.environ.get("LTPU_PALLAS_INTERPRET", "") not in ("", "0")


def pallas_interpret() -> bool:
    """Interpret-mode decision for a ``pl.pallas_call`` site: the env
    lane above, or a CPU default backend (Mosaic kernels cannot
    compile there, so a direct kernel call on CPU — e.g.
    ``split_kernel=pallas`` under ``JAX_PLATFORMS=cpu`` — always runs
    interpreted).  Read at trace time; jit caches key on shapes/static
    args only, so flip the env before the first kernel trace."""
    if pallas_interpret_forced():
        return True
    try:
        import jax
        return jax.default_backend() == "cpu"
    except Exception:  # pragma: no cover - jax not importable
        return False


def strip_non_cpu_backends() -> None:
    """Drop accelerator backend factories registered by interpreter
    startup hooks (e.g. a site-wide PJRT plugin) so CPU-only runs can
    never block on accelerator-tunnel health.  No-op unless
    ``JAX_PLATFORMS`` requests cpu; best-effort — the registry is a
    private jax internal."""
    if "cpu" not in os.environ.get("JAX_PLATFORMS", ""):
        return
    try:
        import jax
        import jax._src.xla_bridge as xb

        # Pallas registers TPU lowering rules at import time and
        # requires the "tpu" platform NAME to still be known — import
        # it before dropping the factories so the interpret-mode CPU
        # lane (split/histogram kernels under pallas_interpret) can
        # import the module from cache afterwards
        try:
            import jax.experimental.pallas  # noqa: F401
            from jax.experimental.pallas import tpu  # noqa: F401
        except Exception:  # pragma: no cover - pallas-less builds
            pass
        # site startup hooks may have already forced a different
        # platform selection through jax.config (overriding the env
        # var) — pin the config itself back to cpu
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
        for name in [k for k in xb._backend_factories if k != "cpu"]:
            xb._backend_factories.pop(name, None)
    except (ImportError, AttributeError):  # pragma: no cover
        pass
