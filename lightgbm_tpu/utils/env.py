"""Environment hygiene helpers for hermetic CPU runs."""
from __future__ import annotations

import os


def strip_non_cpu_backends() -> None:
    """Drop accelerator backend factories registered by interpreter
    startup hooks (e.g. a site-wide PJRT plugin) so CPU-only runs can
    never block on accelerator-tunnel health.  No-op unless
    ``JAX_PLATFORMS`` requests cpu; best-effort — the registry is a
    private jax internal."""
    if "cpu" not in os.environ.get("JAX_PLATFORMS", ""):
        return
    try:
        import jax
        import jax._src.xla_bridge as xb

        # site startup hooks may have already forced a different
        # platform selection through jax.config (overriding the env
        # var) — pin the config itself back to cpu
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
        for name in [k for k in xb._backend_factories if k != "cpu"]:
            xb._backend_factories.pop(name, None)
    except (ImportError, AttributeError):  # pragma: no cover
        pass
