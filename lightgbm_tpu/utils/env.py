"""Environment hygiene helpers for hermetic CPU runs."""
from __future__ import annotations

import os


def force_host_platform_devices(n: int) -> None:
    """Append ``--xla_force_host_platform_device_count=n`` to
    ``XLA_FLAGS`` so the CPU platform exposes ``n`` virtual devices —
    the mesh the sharded tests/benches run on.  Must be called BEFORE
    the first jax import; no-op when the flag is already present (an
    explicit operator choice wins) or ``n <= 1``.  The flag only
    affects the host platform, so it is safe to set even when an
    accelerator backend ends up selected."""
    flags = os.environ.get("XLA_FLAGS", "")
    if n <= 1 or "xla_force_host_platform_device_count" in flags:
        return
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n)}"
    ).strip()


def strip_non_cpu_backends() -> None:
    """Drop accelerator backend factories registered by interpreter
    startup hooks (e.g. a site-wide PJRT plugin) so CPU-only runs can
    never block on accelerator-tunnel health.  No-op unless
    ``JAX_PLATFORMS`` requests cpu; best-effort — the registry is a
    private jax internal."""
    if "cpu" not in os.environ.get("JAX_PLATFORMS", ""):
        return
    try:
        import jax
        import jax._src.xla_bridge as xb

        # site startup hooks may have already forced a different
        # platform selection through jax.config (overriding the env
        # var) — pin the config itself back to cpu
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
        for name in [k for k in xb._backend_factories if k != "cpu"]:
            xb._backend_factories.pop(name, None)
    except (ImportError, AttributeError):  # pragma: no cover
        pass
