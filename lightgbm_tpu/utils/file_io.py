"""Virtual file IO: local paths plus remote schemes.

Reference: ``src/io/file_io.cpp:53-70`` routes paths through
``VirtualFileReader/Writer`` with an HDFS implementation behind
``USE_HDFS`` (libhdfs).  Here remote files are MATERIALIZED to local
temporaries on read and uploaded on write-close — the framework's
readers (native text parser, numpy, binary dataset cache) all want
local random access, and a one-shot copy through the ``hadoop`` CLI
(or ``pyarrow``'s HadoopFileSystem when importable) avoids binding
libhdfs.  Unsupported schemes fail loudly with the recipe.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import tempfile
from contextlib import contextmanager
from typing import Iterator

from .log import Log

__all__ = ["is_remote", "localize", "open_output"]

_REMOTE_SCHEMES = ("hdfs://", "viewfs://")


def is_remote(path) -> bool:
    return isinstance(path, str) and path.startswith(_REMOTE_SCHEMES)


def _hadoop_cli():
    return shutil.which("hadoop") or shutil.which("hdfs")


def _pyarrow_hdfs():
    """pyarrow's generic FileSystem.from_uri — returns (fs, inner
    path); the Hadoop filesystem resolves from the hdfs:// scheme."""
    try:
        from pyarrow import fs as pafs
        return pafs.FileSystem.from_uri
    except Exception:
        return None


_local_cache: dict = {}


def _cleanup_localized() -> None:  # pragma: no cover - exit hook
    for p in _local_cache.values():
        try:
            os.unlink(p)
        except OSError:
            pass
    _local_cache.clear()


def localize(path: str) -> str:
    """A local path with the file's contents; the input itself when it
    is already local.  Remote fetches are cached per URI and the
    temporaries are removed at process exit."""
    if not is_remote(path):
        return path
    cached = _local_cache.get(path)
    if cached is not None and os.path.exists(cached):
        return cached
    if not _local_cache:
        import atexit
        atexit.register(_cleanup_localized)
    tmp = tempfile.NamedTemporaryFile(
        prefix="ltpu_remote_", suffix="_" + os.path.basename(path),
        delete=False)
    tmp.close()
    cli = _hadoop_cli()
    if cli is not None:
        res = subprocess.run([cli, "fs" if cli.endswith("hadoop")
                              else "dfs", "-get", "-f", path, tmp.name],
                             capture_output=True, text=True)
        if res.returncode != 0:
            Log.fatal("failed to fetch %s: %s", path, res.stderr.strip())
        _local_cache[path] = tmp.name
        return tmp.name
    from_uri = _pyarrow_hdfs()
    if from_uri is not None:
        fs, inner = from_uri(path)
        with fs.open_input_stream(inner) as src, \
                open(tmp.name, "wb") as dst:
            shutil.copyfileobj(src, dst)
        _local_cache[path] = tmp.name
        return tmp.name
    Log.fatal("remote path %s needs a 'hadoop' CLI on PATH or pyarrow "
              "with HDFS support; neither is available", path)


@contextmanager
def open_output(path: str, mode: str = "w") -> Iterator:
    """Open ``path`` for writing; remote targets are written locally
    and uploaded on close (``VirtualFileWriter`` contract)."""
    if not is_remote(path):
        with open(path, mode) as f:
            yield f
        return
    tmp = tempfile.NamedTemporaryFile(prefix="ltpu_out_", delete=False)
    tmp.close()
    try:
        with open(tmp.name, mode) as f:
            yield f
        cli = _hadoop_cli()
        if cli is None:
            from_uri = _pyarrow_hdfs()
            if from_uri is None:
                Log.fatal("remote path %s needs a 'hadoop' CLI on PATH "
                          "or pyarrow with HDFS support", path)
            fs, inner = from_uri(path)
            with open(tmp.name, "rb") as src, \
                    fs.open_output_stream(inner) as dst:
                shutil.copyfileobj(src, dst)
        else:
            res = subprocess.run(
                [cli, "fs" if cli.endswith("hadoop") else "dfs", "-put",
                 "-f", tmp.name, path], capture_output=True, text=True)
            if res.returncode != 0:
                Log.fatal("failed to upload %s: %s", path,
                          res.stderr.strip())
    finally:
        try:
            os.unlink(tmp.name)
        except OSError:
            pass
