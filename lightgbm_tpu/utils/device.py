"""Device synchronization helper.

The training loop occasionally needs a BUILD BARRIER — "wait until
this device computation finished" — without paying for its payload:
the split-fetch diagnostic timer, the standalone kernel timers, and
the profilers all want device wall time, not transfer time.

``jax.block_until_ready`` is the native barrier, but on the remote
accelerator tunnel this repo historically trained over (the ``axon``
PJRT plugin) it returns before the computation has landed — the
round-4 profiling sessions measured dispatch time, not compute.  The
workaround was a 1-element ``np.asarray`` fetch — reliable
everywhere, but it costs one extra tunnel round-trip (~120 ms there)
and was copy-pasted inline at three call sites.  This module is the
ONE implementation of that choice:

- local backends (cpu/gpu/tpu — every runtime whose
  ``block_until_ready`` is honest): ``jax.block_until_ready``, free;
- the tunnel backend (platform name matches ``axon``), or
  ``LTPU_SYNC_FETCH=1``: the 1-element fetch fallback
  (``LTPU_SYNC_FETCH=0`` forces the native barrier even there).
"""
from __future__ import annotations

import os

__all__ = ["build_barrier", "sync_fetch_needed"]

_TUNNEL_PLATFORMS = ("axon",)


def sync_fetch_needed() -> bool:
    """True when the barrier must be a 1-element fetch: the operator
    forced it (``LTPU_SYNC_FETCH=1``), or the default backend is a
    remote-tunnel platform whose ``block_until_ready`` returns before
    compute lands.  ``LTPU_SYNC_FETCH=0`` forces the native barrier
    unconditionally."""
    forced = os.environ.get("LTPU_SYNC_FETCH", "")
    if forced == "1":
        return True
    if forced == "0":
        return False
    try:
        import jax

        return jax.default_backend() in _TUNNEL_PLATFORMS
    except Exception:  # pragma: no cover - backend probe must not raise
        return False


def build_barrier(x):
    """Block until the device computation behind ``x`` (an array or a
    pytree of arrays) has completed.  Returns ``x`` so call sites can
    barrier inline.  Transfers at most ONE element (and usually
    nothing): this is a wait, not a fetch."""
    if sync_fetch_needed():
        import numpy as np
        import jax

        leaf = next((l for l in jax.tree_util.tree_leaves(x)
                     if hasattr(l, "reshape")), None)
        if leaf is not None:
            np.asarray(leaf.reshape(-1)[:1])
        return x
    import jax

    return jax.block_until_ready(x)
