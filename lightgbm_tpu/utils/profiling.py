"""Phase timers + device tracing.

The reference accumulates per-phase ``std::chrono`` timers behind the
compile-time ``TIMETAG`` flag (``serial_tree_learner.cpp:161-215``,
``gbdt.cpp:253-256``) and prints them at shutdown.  Here the registry
is always on (the overhead is two clock reads per phase), summarized
on demand; device-side traces come from the JAX profiler.

Usage::

    from lightgbm_tpu.utils.profiling import timed, summary
    with timed("tree"):
        ...
    print(summary())

    with jax_trace("/tmp/tb"):   # view in TensorBoard / xprof
        bst = lgb.train(...)
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Tuple

__all__ = ["timed", "summary", "reset", "get", "snapshot", "delta_ms",
           "jax_trace"]

_lock = threading.Lock()
_acc: Dict[str, Tuple[float, int]] = {}


@contextlib.contextmanager
def timed(name: str) -> Iterator[None]:
    """Accumulate wall time under ``name`` (TIMETAG analog)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            total, count = _acc.get(name, (0.0, 0))
            _acc[name] = (total + dt, count + 1)


def get(name: str) -> Tuple[float, int]:
    """(total seconds, call count) for a phase."""
    with _lock:
        return _acc.get(name, (0.0, 0))


def reset() -> None:
    with _lock:
        _acc.clear()


def snapshot() -> Dict[str, Tuple[float, int]]:
    """Copy of the accumulator — telemetry diffs two snapshots to
    attribute time to phases per iteration."""
    with _lock:
        return dict(_acc)


def delta_ms(before: Dict[str, Tuple[float, int]]) -> Dict[str, float]:
    """Per-phase milliseconds accumulated since ``before`` (a
    :func:`snapshot` result); phases with no new time are omitted."""
    out = {}
    for name, (total, _count) in snapshot().items():
        d = total - before.get(name, (0.0, 0))[0]
        if d > 0:
            out[name] = round(d * 1e3, 3)
    return out


def summary() -> str:
    """One line per phase: name, total, count, mean."""
    with _lock:
        items = sorted(_acc.items(), key=lambda kv: -kv[1][0])
    lines = [f"{name:<24s} {total:10.3f}s  x{count:<7d} "
             f"{total / max(count, 1) * 1e3:9.2f} ms/call"
             for name, (total, count) in items]
    return "\n".join(lines) if lines else "(no phases recorded)"


@contextlib.contextmanager
def jax_trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace viewable in TensorBoard/xprof.  No-op if
    the profiler is unavailable on the backend."""
    import jax

    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:  # pragma: no cover - backend-dependent
        pass
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover
                pass
