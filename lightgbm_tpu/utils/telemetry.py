"""Structured run telemetry: schema-versioned JSONL run records.

Round 5 lost its on-hardware perf evidence because one tunnel outage
turned the bench artifact into a raw traceback, and ``docs/
Benchmarks.md`` drifted because it was written from memory instead of
from artifacts.  This module is the run-record discipline GPU boosting
systems lean on to attribute time to kernels, transfers and comms
(XGBoost: Scalable GPU Accelerated Learning, arXiv:1806.11248;
Out-of-Core GPU Gradient Boosting, arXiv:2005.09148): every training
and inference entry point feeds a :class:`RunRecorder`, which appends
one JSON object per line to ``telemetry_file`` and logs an aggregate
summary through :class:`~lightgbm_tpu.utils.log.Log` at shutdown.

Record stream (all records carry ``schema``/``type``/``seq``/``wall_time``):

- ``run_start``  — backend identity (platform, device kind, degraded
  flags), the tier/gate decision for the booster (two_col vs wave vs
  routed vs exact, with the gate that rejected each higher tier),
  config subset, device memory stats when the backend exposes them.
- ``iteration``  — per boosting iteration: phase-timer deltas from
  ``profiling.py``, XLA compile/retrace counter deltas (hooked via
  ``jax.monitoring``, so a silent retrace storm becomes a visible
  number), histogram passes + pool hit rate, per-learner collective
  payload bytes, trees added.
- ``superstep``  — one record per fused K-iteration block
  (``fused_iters`` > 1, ``models/gbdt.py``): the block's first
  iteration, K, and the AMORTIZED phase/counter deltas — per-iteration
  wall time is ``duration_ms / k``, which is how ``triage_run.py``
  normalizes it (a K-fold drop in per-iteration time is the fused
  path working, not an anomaly).
- ``eval``       — metric results as the training loop computed them.
- ``predict``    — one per predict call: rows, trees, engine on/off,
  predict-engine compile-cache hit/miss/eviction deltas.
- ``run_end``    — the aggregate summary (also Log.info'd).

Consumers: ``tools/triage_run.py`` (anomaly triage + ``--check``
schema lint) and ``tools/render_benchmarks.py`` (regenerates
``docs/Benchmarks.md`` from artifacts).  The bench-artifact recovery
parser lives here too so ``bench.py`` and the tools share one
implementation — and it must stay importable WITHOUT jax (the bench's
outage path runs when the backend cannot even initialize).
"""
from __future__ import annotations

import atexit
import glob
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .log import Log

__all__ = [
    "SCHEMA_VERSION", "RECORD_TYPES", "RunRecorder", "counters",
    "counters_snapshot", "install_jax_hooks", "validate_record",
    "lint_file", "read_records", "parse_bench_artifact",
    "latest_good_bench", "get_recorder", "set_recorder", "percentile",
    "set_trace_provider", "add_emit_observer", "remove_emit_observer",
]


def percentile(sorted_vals, q: float) -> float:
    """Index-based percentile over an ascending-sorted sequence — the
    ONE implementation every latency rollup shares (run_end summary,
    serve stats, bench, loadgen), so their p50/p95/p99 agree."""
    if not sorted_vals:
        return 0.0
    return float(sorted_vals[min(int(q * len(sorted_vals)),
                                 len(sorted_vals) - 1)])

SCHEMA_VERSION = 1

RECORD_TYPES = ("run_start", "iteration", "superstep", "eval", "predict",
                "serve", "explain", "checkpoint", "fleet", "continual",
                "recovery", "router", "ingest", "span", "capture", "sweep",
                "slo", "autoscale", "pager", "run_end")

# per-type required fields on top of the common envelope; values are
# (field, type-or-types) pairs the lint enforces
_COMMON_FIELDS = (("schema", int), ("type", str), ("seq", int),
                  ("wall_time", float))
_TYPE_FIELDS: Dict[str, Tuple[Tuple[str, Any], ...]] = {
    "run_start": (("backend", str),),
    "iteration": (("iter", int), ("duration_ms", (int, float))),
    # one record per fused K-iteration super-step (fused_iters > 1):
    # ``iter`` is the block's first iteration, ``k`` the block size,
    # ``duration_ms``/``phases_ms``/``counters`` cover the WHOLE block
    # (per-iteration cost = value / k).  SHARDED super-steps (a
    # distributed tree learner running inside the fused scan,
    # docs/Distributed.md) additionally carry ``learner``,
    # ``num_shards``, ``mesh_shape`` and the per-block per-shard
    # ``collective_bytes``/``collective_ops`` estimates — the series
    # triage_run.py's weak-scaling anomaly reads.  Async-pipelined
    # runs (superstep_pipeline_depth > 0) add ``pipeline_depth`` (the
    # configured in-flight depth) and ``fetch_overlap_s`` (wall
    # between the block's dispatch and its fetch — the window its
    # device compute overlapped host work); triage_run.py flags
    # depth > 0 with ~zero overlap as pipelining silently disabled.
    # ``split_kernel`` records the best-split engine that ran inside
    # the block (pallas = the fused histogram→split kernel tier, xla
    # = the vectorized scans) and ``split_fallback`` the tier gate
    # that rejected the kernel tier when it did; triage_run.py flags
    # an XLA fallback on a TPU backend as MED.
    "superstep": (("iter", int), ("k", int),
                  ("duration_ms", (int, float))),
    "eval": (("iter", int), ("results", list)),
    "predict": (("rows", int), ("n_trees", int), ("engine", bool)),
    # one record per ONLINE serving request (serve/server.py):
    # ``status`` is ok|shed|timeout|rejected|error|swap; ok records
    # carry the queue_ms/assemble_ms/dispatch_ms latency split plus
    # batch_rows/bucket_rows/occupancy for their dispatch unit, and
    # the model ``version`` that scored them.  The run_end summary
    # rolls up p50/p95/p99 total latency and shed/timeout counts.
    "serve": (("status", str), ("rows", int),
              ("total_ms", (int, float))),
    # one record per ONLINE explanation request (serve/server.py, the
    # /explain lane): same envelope and status vocabulary as ``serve``
    # plus ``xla_compiles`` — the compile-counter DELTA measured across
    # the request's device SHAP dispatch.  Steady state must be 0 (the
    # publish-time warmup pre-compiles every explain bucket); a
    # non-zero value past warmup is the explanation engine silently
    # recompiling per request (MED anomaly ``explain_compile``,
    # obs/rules.py).  The run_end summary rolls up request/row counts
    # and p50/p95/p99 explain latency separately from the predict lane.
    "explain": (("status", str), ("rows", int),
                ("total_ms", (int, float))),
    # one record per checkpoint event (ckpt/manager.py): ``event`` is
    # save|load|fallback; saves carry iter/reason(periodic|preempt|
    # final)/bytes, loads carry iter/bytes, fallbacks carry the
    # rejected path + validation error.  The run_end summary rolls up
    # counts, total bytes and total save/load time; triage_run.py
    # flags fallbacks and save overhead > 5% of train wall time.
    "checkpoint": (("event", str), ("duration_ms", (int, float))),
    # one record per resilience-layer event (serve/fleet.py,
    # serve/watcher.py): ``event`` is replica_start|replica_exit|
    # replica_restart|circuit_open|circuit_half_open (supervisor) or
    # publish|publish_verified|publish_unverified|publish_skip|
    # rollback|watch_error (watcher / rollback controller).  publish
    # records carry model_id/path/iter; publish_skip carries
    # reason=manifest|canary|holddown|error + the validation error;
    # rollback carries reason=error_rate|p99|stats_reset|forced +
    # from_id/to_id.  triage_run.py
    # summarizes them and flags skips, rollbacks and open circuits.
    "fleet": (("event", str),),
    # one record per continual-training-loop event (lightgbm_tpu/cont/
    # and the numerical-health guard, utils/health.py): ``event`` is
    # batch (one consumed batch: batch/rows/iter/mode=extend|refit/
    # duration_ms) | quarantine (reason=validate|nonfinite|read|stall|
    # error + batch + error detail) | backoff (a transient ingest read
    # retried: batch/attempt/sleep_s) | stall_restart (the watchdog
    # abandoned a wedged train step: batch/attempt/stalled_s) |
    # nonfinite (the numerical-health guard tripped: iter/phase —
    # also emitted by one-shot engine.train) | batch_error (a train
    # attempt raised: batch/attempt/error) | preempt | resume |
    # idle_exit | fault_unknown_point (utils/faults.py typo warning).
    # triage_run.py rolls up quarantine rate, stall restarts and
    # non-finite rewinds as anomalies.
    "continual": (("event", str),),
    # one record per elastic-recovery event (parallel/elastic.py and
    # the cross-width resume path, ckpt/manager.py): ``event`` is
    # detect (a shard failure was classified: cause=hang|error +
    # detail/iter/num_shards) | remesh (recovery rebuilt the mesh:
    # from_shards/to_shards/iter/cause/duration_ms) | remesh_failed
    # (one re-mesh attempt raised; recovery degrades further) |
    # reshard (a checkpoint taken on one mesh topology restored onto
    # another: from_shards/to_shards + learners) | escalate (recovery
    # budget exhausted: reason=max_remesh|min_shards — the run fails
    # loudly into the checkpoint restart story).  triage_run.py rolls
    # these up and flags repeated re-meshes of one run as HIGH.
    "recovery": (("event", str),),
    # one record per routing-front event (serve/router.py): ``event``
    # is request (one CLIENT-facing routed request: model/status/rows/
    # total_ms/attempts/retries + hedged/hedge_won when the tail-
    # latency hedge fired — status ok|shed|backpressure|timeout|
    # upstream|no_backend|unknown_model|bad_request (shed = the
    # router's own admission budget; backpressure = every backend
    # answered 429/503 and the hint passed through); a request that
    # needed a
    # retry or a hedge and still answered 200 is status ok, failures
    # made invisible being the router's whole job) | breaker_open /
    # breaker_close (the per-backend circuit breaker feeding the
    # balancer: backend + failures) | scrape_error (a /healthz scrape
    # failed).  The run_end summary rolls up request/hedge/shed/retry
    # counts and p50/p95/p99 routed latency; obs/rules.py flags hedge
    # rate > 20% (MED), budget-shed rate > 5% (HIGH) and breaker
    # opens (HIGH).
    "router": (("event", str),),
    # one record per streamed-ingest event (io/stream.py + io/cache.py,
    # docs/Streaming.md): ``event`` is chunk_read (one raw chunk off
    # the source: chunk/rows/attempt) | cache_write (one binned chunk
    # published: chunk/bytes/bin_ms/write_ms, rebin=true when it
    # REPLACED a corrupt cached chunk) | verify_fail (a cached chunk
    # failed its sha256 verify-on-load and will be re-binned alone) |
    # prelude_hit (the fit-once mappers + metadata were reused —
    # resume never fits a mapper twice) | fit_mappers (the streamed
    # sample pass ran: rows_sampled/duration_ms) | backoff (a
    # transient chunk read or prefetch window retried:
    # chunk|window/attempt/sleep_s) | quarantine (retries exhausted or
    # deterministic parse failure: chunk/reason — a HIGH anomaly,
    # obs/rules.py) | clamp (stream_chunk_rows degraded to fit
    # stream_host_budget_mb) | prefetch (one host->device upload:
    # windows/bytes/overlap_s — the host prep hidden under async
    # device copies; ~zero overlap with streaming enabled is a MED
    # anomaly) | ingest_done (rollup: chunks/cache_hits/rebinned/
    # from_cache) | resume (checkpoint restore compared the manifest's
    # recorded cache identity with the live dataset's: cache_hit=false
    # means a re-bin the manifest should have prevented — MED).
    "ingest": (("event", str),),
    # one record per device-block pager flush (io/pager.py via
    # models/gbdt.py): ``event`` is flush (per-iteration/per-block
    # DELTA stats: pages served, bytes paged, overlap_s of prep
    # hidden on the prefetch thread, wait_s the device program
    # blocked in callbacks, stalls = serve-path inline preps, spills/
    # evictions/spill_hits of the host spill cache, page_rows/
    # n_pages geometry) | done (cumulative rollup at train end).
    # obs/rules.py flags paging active with ~zero prefetch overlap
    # as MED (pager_no_overlap).
    "pager": (("event", str),),
    # one record per closed trace span (obs/spans.py): ``trace_id``
    # joins spans (and trace-tagged records of every other type)
    # emitted by ANY process into one timeline — the continual
    # daemon's per-batch root, the checkpoint save, the watcher's
    # validate/canary/publish and the first request the published
    # version serves all share one trace_id across OS processes
    # (env / HTTP-header / checkpoint-extra propagation).
    # ``parent_id`` is absent on trace roots; ``status`` is ok|error.
    # ``tools/trace_view.py`` renders the joined timeline.
    "span": (("name", str), ("trace_id", str), ("span_id", str),
             ("duration_ms", (int, float))),
    # one record per flight-recorder capture (obs/flight.py):
    # ``trigger`` is the firing rule code (retrace_storm |
    # pipelining_disabled | xla_fallback | stall | rollback |
    # nonfinite), ``path`` the capture directory holding
    # anomaly.json + ring.jsonl (+ profile/ on device backends).
    "capture": (("trigger", str), ("path", str)),
    # one record per battery sweep (models/battery.py + engine.sweep,
    # docs/Sweep.md): ``models`` is the battery width B, ``groups``
    # the number of distinct compiled programs (static-signature
    # groups — every member whose program-shaping params agree shares
    # ONE vmapped compile), ``xla_compiles`` the compile-counter delta
    # across the batched dispatches and ``retraces_per_model`` the
    # per-model compile count BEYOND the one expected warmup compile
    # per group — steady-state must be 0 (one compiled program serves
    # the whole battery); a positive value is the battery silently
    # degrading toward per-model compilation (MED anomaly,
    # obs/rules.py, surfaced by triage_run.py).  Also carries the
    # models/s rollup plus per-model best iterations and CV scores.
    "sweep": (("models", int), ("groups", int), ("xla_compiles", int),
              ("retraces_per_model", (int, float)),
              ("models_per_s", (int, float))),
    # one record per SLO objective per evaluation tick (obs/slo.py):
    # ``objective`` names the declared objective (availability |
    # latency_p99 | queue_saturation | shed:<model> | custom),
    # ``status`` is ok | slow_burn | fast_burn | budget_exhausted |
    # scrape_error (the source raised; the tick degraded to last-known
    # state).  Carries the multi-window burn rates
    # (burn_fast/burn_mid/burn_slow), budget_remaining (fraction of
    # the error budget left this period — persisted across restarts),
    # exhaustion_eta_s (-1 = not burning) and the window/period
    # good/bad totals.  obs/rules.py turns the statuses into anomalies
    # (budget-exhaustion HIGH, fast-burn HIGH, slow-burn MED) so
    # --follow, triage and the flight recorder all see SLO state.
    "slo": (("objective", str), ("status", str)),
    # one record per autoscaler decision (serve/autoscaler.py):
    # ``action`` is grow | drain | retune_shed | retune_restore | none
    # (a degraded decide), ``mode`` is active | dry_run | degraded,
    # ``rule`` the policy clause that fired (fast_burn |
    # queue_saturation | budget_floor | burn_cleared | idle |
    # decide_error), and ``evidence`` the full inputs snapshot the
    # decision was made from (burn rates, queue fraction, replica and
    # breaker counts) — the reconciliation surface the chaos e2e
    # diffs against actual fleet/router state changes.  grow/drain
    # carry from_replicas/to_replicas; retunes carry rows_per_s.
    "autoscale": (("action", str), ("mode", str)),
    "run_end": (("summary", dict),),
}


# ----------------------------------------------------------------------
# process-wide counters (compile/retrace events, predict-cache traffic)
# ----------------------------------------------------------------------
class _Counters:
    """Thread-safe monotonic counters; recorders snapshot-and-diff.
    Hooks (``add_hook``) observe every increment — the obs metrics
    registry mirrors the counters into Prometheus series through one
    (``obs/metrics.py``), so live scrapes and run_end rollups agree
    bit-for-bit."""

    def __init__(self):
        self._lock = threading.Lock()
        self._c: Dict[str, float] = {}
        self._hooks: List[Any] = []

    def incr(self, name: str, by: float = 1.0) -> None:
        # hooks fire INSIDE the lock: paired with add_hook's atomic
        # prime-then-register, no increment can land between a
        # mirror's seed snapshot and its hook activation (which would
        # skew the bit-for-bit scrape oracle forever).  Hooks must not
        # call back into incr.
        with self._lock:
            self._c[name] = self._c.get(name, 0.0) + by
            for fn in self._hooks:
                try:
                    fn(name, by)
                except Exception:  # noqa: BLE001 - hooks never break
                    pass

    def add_hook(self, fn, prime=None) -> None:
        """Register an increment hook.  ``prime`` (if given) runs
        UNDER the counter lock with a snapshot of current values
        immediately before the hook activates — the atomic
        seed-then-subscribe a mirror needs."""
        with self._lock:
            if fn in self._hooks:
                return
            if prime is not None:
                try:
                    prime(dict(self._c))
                except Exception:  # noqa: BLE001
                    pass
            self._hooks = self._hooks + [fn]

    def remove_hook(self, fn) -> None:
        with self._lock:
            self._hooks = [h for h in self._hooks if h is not fn]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._c)


counters = _Counters()


def counters_snapshot() -> Dict[str, float]:
    return counters.snapshot()


_HOOKS_INSTALLED = False
_HOOKS_LOCK = threading.Lock()


def install_jax_hooks() -> bool:
    """Register ``jax.monitoring`` listeners feeding the process-wide
    compile/retrace counters.  Idempotent; returns False when the
    monitoring API is unavailable.  Event mapping (measured on jax
    0.4.x): ``.../backend_compile_duration`` fires once per REAL XLA
    compile (silent on executable-cache hits), ``.../jaxpr_trace_
    duration`` fires per abstract trace — a flat compile counter with a
    climbing trace counter is the signature of a retrace storm served
    from the compile cache, both climbing is new-shape compilation."""
    global _HOOKS_INSTALLED
    with _HOOKS_LOCK:
        if _HOOKS_INSTALLED:
            return True
        try:
            import jax.monitoring as monitoring
        except Exception:  # pragma: no cover - ancient jax
            return False

        def _on_duration(name, secs, **kw):
            if name.endswith("backend_compile_duration"):
                counters.incr("xla_compiles")
                counters.incr("xla_compile_secs", secs)
            elif name.endswith("jaxpr_trace_duration"):
                counters.incr("jax_traces")
                counters.incr("jax_trace_secs", secs)

        def _on_event(name, **kw):
            if "cache_miss" in name:
                counters.incr("jax_cache_misses")

        # register each listener independently: the two APIs changed
        # at different jax releases, and a partial success must still
        # mark the hooks installed (re-registering the survivor on the
        # next call would double-count every compile)
        ok = False
        try:
            monitoring.register_event_duration_secs_listener(_on_duration)
            ok = True
        except Exception:  # pragma: no cover
            pass
        try:
            monitoring.register_event_listener(_on_event)
            ok = True
        except Exception:  # pragma: no cover
            pass
        _HOOKS_INSTALLED = ok
        return ok


# ----------------------------------------------------------------------
# obs-plane hooks: trace tagging + emit observers
# ----------------------------------------------------------------------
# set by obs/spans.py at import: () -> Optional[(trace_id, span_id)].
# When a span is active, every emitted record is tagged with the
# trace context, so ANY record type joins its trace without the call
# site knowing about tracing.
_TRACE_PROVIDER: Optional[Any] = None

# observers see every record ANY recorder in this process emits (the
# flight recorder's ring + online anomaly rules, obs/flight.py);
# called OUTSIDE the recorder lock with (record, recorder)
_EMIT_OBSERVERS: List[Any] = []
_OBSERVER_LOCK = threading.Lock()


def set_trace_provider(fn) -> None:
    global _TRACE_PROVIDER
    _TRACE_PROVIDER = fn


def add_emit_observer(fn) -> None:
    with _OBSERVER_LOCK:
        if fn not in _EMIT_OBSERVERS:
            _EMIT_OBSERVERS.append(fn)


def remove_emit_observer(fn) -> None:
    with _OBSERVER_LOCK:
        if fn in _EMIT_OBSERVERS:
            _EMIT_OBSERVERS.remove(fn)


# ----------------------------------------------------------------------
# recorder
# ----------------------------------------------------------------------
_OPEN_RECORDERS: List["RunRecorder"] = []
_OPEN_LOCK = threading.Lock()
_GLOBAL: Optional["RunRecorder"] = None


def _atexit_close():  # pragma: no cover - exercised via CLI/bench runs
    with _OPEN_LOCK:
        recs = list(_OPEN_RECORDERS)
    for r in recs:
        try:
            r.close()
        except Exception:
            pass


atexit.register(_atexit_close)


def get_recorder() -> Optional["RunRecorder"]:
    """The process-default recorder (set by the CLI / bench), if any."""
    return _GLOBAL


def set_recorder(rec: Optional["RunRecorder"]) -> None:
    global _GLOBAL
    _GLOBAL = rec


class RunRecorder:
    """Collects run records and appends them as JSONL.

    Thread-safe: ``emit`` may be called from concurrent predict
    threads.  When ``path`` is falsy the records are kept in memory
    only (``self.records``) — the test/tooling mode."""

    def __init__(self, path: Optional[str] = None,
                 run_info: Optional[Dict[str, Any]] = None,
                 keep_records: Optional[bool] = None):
        self._lock = threading.RLock()
        self.path = path or None
        self._fh = open(self.path, "a", buffering=1) if self.path else None
        self.keep_records = (not self.path) if keep_records is None \
            else bool(keep_records)
        self.records: List[Dict[str, Any]] = []
        self._seq = 0
        self._closed = False
        self._t0 = time.time()
        # aggregates for the shutdown summary
        self._agg: Dict[str, float] = {}
        self._phase_totals: Dict[str, float] = {}
        self._tier: Optional[str] = None
        self._backend: Optional[str] = None
        # serve-latency ring for the close-time p50/p95/p99 rollup:
        # bounded (long-running servers must not grow the recorder)
        # and holding the most RECENT 64k samples, so the rollup
        # reflects current behavior, not the first hour's
        self._serve_lat: List[float] = []
        self._serve_lat_n = 0
        self._serve_occ_sum = 0.0
        self._serve_occ_n = 0
        self._explain_lat: List[float] = []
        self._explain_lat_n = 0
        # routed-request latency ring (serve/router.py), same bounded
        # most-recent-samples policy as the serve ring
        self._router_lat: List[float] = []
        self._router_lat_n = 0
        self._base = counters.snapshot()
        install_jax_hooks()
        with _OPEN_LOCK:
            _OPEN_RECORDERS.append(self)
        # the header record must satisfy its own schema even for a bare
        # recorder (no run_info yet): attach_telemetry emits a second,
        # fully-populated run_start once a booster adopts the recorder
        info = dict(run_info or {})
        info.setdefault("backend", "unknown")
        self.emit("run_start", **info)

    # ------------------------------------------------------------------
    def counters_delta(self, last: Dict[str, float]
                       ) -> Tuple[Dict[str, float], Dict[str, float]]:
        """(delta since ``last``, fresh snapshot).  The caller owns the
        snapshot so concurrent iteration/predict streams don't steal
        each other's deltas."""
        now = counters.snapshot()
        delta = {k: round(v - last.get(k, 0.0), 6)
                 for k, v in now.items() if v != last.get(k, 0.0)}
        return delta, now

    def emit(self, rtype: str, **fields) -> Dict[str, Any]:
        rec = {"schema": SCHEMA_VERSION, "type": rtype,
               "wall_time": round(time.time(), 3)}
        rec.update(fields)
        # trace tagging: records emitted under an active span join its
        # trace (span records carry their OWN ids and are left alone)
        if _TRACE_PROVIDER is not None and rtype != "span" \
                and "trace_id" not in rec:
            try:
                ctx = _TRACE_PROVIDER()
            except Exception:  # noqa: BLE001 - tagging is best-effort
                ctx = None
            if ctx is not None:
                rec["trace_id"], rec["span_id"] = ctx
        with self._lock:
            if self._closed:
                return rec
            rec["seq"] = self._seq
            self._seq += 1
            self._aggregate(rec)
            if self.keep_records:
                self.records.append(rec)
            if self._fh is not None:
                # one atomic write per record: concurrent emitters must
                # never interleave partial lines
                self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
        if _EMIT_OBSERVERS:
            with _OBSERVER_LOCK:
                observers = list(_EMIT_OBSERVERS)
            for fn in observers:
                try:
                    fn(rec, self)
                except Exception:  # noqa: BLE001 - observers never break
                    pass
        return rec

    def _aggregate(self, rec: Dict[str, Any]) -> None:
        t = rec.get("type")
        if t == "run_start":
            self._backend = rec.get("backend")
            tier = rec.get("tier")
            if isinstance(tier, dict):
                self._tier = tier.get("tier")
        elif t in ("iteration", "superstep"):
            # a superstep record stands for k iterations
            k = int(rec.get("k", 1)) if t == "superstep" else 1
            self._agg["iterations"] = self._agg.get("iterations", 0) + k
            self._agg["train_ms"] = self._agg.get("train_ms", 0.0) + \
                float(rec.get("duration_ms", 0.0))
            for name, ms in (rec.get("phases_ms") or {}).items():
                self._phase_totals[name] = \
                    self._phase_totals.get(name, 0.0) + float(ms)
            for key in ("xla_compiles", "xla_compile_secs", "jax_traces"):
                v = (rec.get("counters") or {}).get(key)
                if v:
                    self._agg[key] = self._agg.get(key, 0.0) + float(v)
            self._agg["hist_passes"] = self._agg.get("hist_passes", 0.0) \
                + float(rec.get("hist_passes", 0.0))
            self._agg["collective_bytes"] = \
                self._agg.get("collective_bytes", 0.0) + \
                float(rec.get("collective_bytes", 0.0))
            self._agg["collective_ops"] = \
                self._agg.get("collective_ops", 0.0) + \
                float(rec.get("collective_ops", 0.0))
        elif t == "serve":
            status = rec.get("status")
            if status == "swap":
                self._agg["serve_swaps"] = \
                    self._agg.get("serve_swaps", 0) + 1
                return
            self._agg["serve_requests"] = \
                self._agg.get("serve_requests", 0) + 1
            self._agg["serve_rows"] = \
                self._agg.get("serve_rows", 0) + int(rec.get("rows", 0))
            if status != "ok":
                self._agg[f"serve_{status}"] = \
                    self._agg.get(f"serve_{status}", 0) + 1
                return
            v = float(rec.get("total_ms", 0.0))
            if len(self._serve_lat) < 65536:
                self._serve_lat.append(v)
            else:
                self._serve_lat[self._serve_lat_n % 65536] = v
            self._serve_lat_n += 1
            occ = rec.get("occupancy")
            if occ is not None:
                self._serve_occ_sum += float(occ)
                self._serve_occ_n += 1
        elif t == "explain":
            status = rec.get("status")
            self._agg["explain_requests"] = \
                self._agg.get("explain_requests", 0) + 1
            self._agg["explain_rows"] = \
                self._agg.get("explain_rows", 0) + int(rec.get("rows", 0))
            compiles = float(rec.get("xla_compiles", 0.0) or 0.0)
            if compiles:
                self._agg["explain_compiles"] = \
                    self._agg.get("explain_compiles", 0.0) + compiles
            if status != "ok":
                self._agg[f"explain_{status}"] = \
                    self._agg.get(f"explain_{status}", 0) + 1
                return
            v = float(rec.get("total_ms", 0.0))
            if len(self._explain_lat) < 65536:
                self._explain_lat.append(v)
            else:
                self._explain_lat[self._explain_lat_n % 65536] = v
            self._explain_lat_n += 1
        elif t == "checkpoint":
            event = rec.get("event")
            if event in ("save", "load", "fallback"):
                self._agg[f"ckpt_{event}s"] = \
                    self._agg.get(f"ckpt_{event}s", 0) + 1
            if event in ("save", "load"):
                self._agg[f"ckpt_{event}_ms"] = round(
                    self._agg.get(f"ckpt_{event}_ms", 0.0) +
                    float(rec.get("duration_ms", 0.0)), 3)
            if event == "save":
                self._agg["ckpt_bytes"] = \
                    self._agg.get("ckpt_bytes", 0) + \
                    int(rec.get("bytes", 0))
        elif t == "fleet":
            key = {
                "replica_start": "fleet_replica_starts",
                "replica_exit": "fleet_replica_exits",
                "replica_restart": "fleet_restarts",
                "circuit_open": "fleet_circuit_opens",
                "publish": "fleet_publishes",
                "publish_verified": "fleet_publish_verified",
                "publish_unverified": "fleet_publish_unverified",
                "publish_skip": "fleet_skips",
                "rollback": "fleet_rollbacks",
                "watch_error": "fleet_watch_errors",
            }.get(rec.get("event"))
            if key:
                self._agg[key] = self._agg.get(key, 0) + 1
        elif t == "continual":
            event = rec.get("event")
            key = {
                "batch": "continual_batches",
                "quarantine": "continual_quarantines",
                "backoff": "continual_backoffs",
                "stall_restart": "continual_stall_restarts",
                "nonfinite": "continual_nonfinite",
                "batch_error": "continual_batch_errors",
                "resume": "continual_resumes",
            }.get(event)
            if key:
                self._agg[key] = self._agg.get(key, 0) + 1
            if event == "batch":
                self._agg["continual_rows"] = \
                    self._agg.get("continual_rows", 0) + \
                    int(rec.get("rows", 0))
                self._agg["continual_batch_ms"] = round(
                    self._agg.get("continual_batch_ms", 0.0) +
                    float(rec.get("duration_ms", 0.0)), 3)
        elif t == "router":
            event = rec.get("event")
            if event == "breaker_open":
                self._agg["router_breaker_opens"] = \
                    self._agg.get("router_breaker_opens", 0) + 1
                return
            if event != "request":
                return
            status = rec.get("status")
            self._agg["router_requests"] = \
                self._agg.get("router_requests", 0) + 1
            self._agg["router_rows"] = \
                self._agg.get("router_rows", 0) + int(rec.get("rows", 0))
            self._agg["router_retries"] = \
                self._agg.get("router_retries", 0) + \
                int(rec.get("retries", 0))
            if rec.get("hedged"):
                self._agg["router_hedges"] = \
                    self._agg.get("router_hedges", 0) + 1
                if rec.get("hedge_won"):
                    self._agg["router_hedge_wins"] = \
                        self._agg.get("router_hedge_wins", 0) + 1
            if status != "ok":
                self._agg[f"router_{status}"] = \
                    self._agg.get(f"router_{status}", 0) + 1
                return
            v = float(rec.get("total_ms", 0.0))
            if len(self._router_lat) < 65536:
                self._router_lat.append(v)
            else:
                self._router_lat[self._router_lat_n % 65536] = v
            self._router_lat_n += 1
        elif t == "ingest":
            event = rec.get("event")
            key = {
                "chunk_read": "ingest_chunk_reads",
                "cache_write": "ingest_cache_writes",
                "verify_fail": "ingest_verify_fails",
                "prelude_hit": "ingest_prelude_hits",
                "fit_mappers": "ingest_mapper_fits",
                "backoff": "ingest_backoffs",
                "quarantine": "ingest_quarantines",
                "clamp": "ingest_clamps",
                "resume": "ingest_resumes",
            }.get(event)
            if key:
                self._agg[key] = self._agg.get(key, 0) + 1
            if event == "cache_write":
                self._agg["ingest_cached_bytes"] = \
                    self._agg.get("ingest_cached_bytes", 0) + \
                    int(rec.get("bytes", 0))
                if rec.get("rebin"):
                    self._agg["ingest_rebins"] = \
                        self._agg.get("ingest_rebins", 0) + 1
            elif event == "chunk_read":
                self._agg["ingest_rows"] = \
                    self._agg.get("ingest_rows", 0) + \
                    int(rec.get("rows", 0))
            elif event == "prefetch":
                self._agg["ingest_prefetch_windows"] = \
                    self._agg.get("ingest_prefetch_windows", 0) + \
                    int(rec.get("windows", 0))
                self._agg["ingest_prefetch_overlap_s"] = round(
                    self._agg.get("ingest_prefetch_overlap_s", 0.0) +
                    float(rec.get("overlap_s", 0.0)), 6)
            elif event == "ingest_done":
                self._agg["ingest_runs"] = \
                    self._agg.get("ingest_runs", 0) + 1
                self._agg["ingest_cache_hits"] = \
                    self._agg.get("ingest_cache_hits", 0) + \
                    int(rec.get("cache_hits", 0))
            elif event == "resume" and not rec.get("cache_hit", True):
                self._agg["ingest_resume_misses"] = \
                    self._agg.get("ingest_resume_misses", 0) + 1
        elif t == "pager":
            if rec.get("event") == "flush":
                for field, key in (("pages", "pager_pages"),
                                   ("bytes", "pager_bytes"),
                                   ("stalls", "pager_stalls")):
                    self._agg[key] = self._agg.get(key, 0) + \
                        int(rec.get(field, 0))
                self._agg["pager_overlap_s"] = round(
                    self._agg.get("pager_overlap_s", 0.0) +
                    float(rec.get("overlap_s", 0.0)), 6)
                self._agg["pager_wait_s"] = round(
                    self._agg.get("pager_wait_s", 0.0) +
                    float(rec.get("wait_s", 0.0)), 6)
        elif t == "recovery":
            key = {
                "detect": "recovery_detects",
                "remesh": "recovery_remeshes",
                "remesh_failed": "recovery_remesh_failures",
                "reshard": "recovery_reshards",
                "escalate": "recovery_escalations",
            }.get(rec.get("event"))
            if key:
                self._agg[key] = self._agg.get(key, 0) + 1
        elif t == "slo":
            self._agg["slo_evals"] = self._agg.get("slo_evals", 0) + 1
            status = rec.get("status")
            if status and status != "ok":
                self._agg[f"slo_{status}"] = \
                    self._agg.get(f"slo_{status}", 0) + 1
        elif t == "autoscale":
            action = rec.get("action")
            if action and action != "none":
                self._agg["autoscale_actions"] = \
                    self._agg.get("autoscale_actions", 0) + 1
                self._agg[f"autoscale_{action}"] = \
                    self._agg.get(f"autoscale_{action}", 0) + 1
            if rec.get("mode") == "degraded":
                self._agg["autoscale_degraded"] = \
                    self._agg.get("autoscale_degraded", 0) + 1
        elif t == "span":
            self._agg["spans"] = self._agg.get("spans", 0) + 1
        elif t == "capture":
            self._agg["captures"] = self._agg.get("captures", 0) + 1
        elif t == "predict":
            self._agg["predicts"] = self._agg.get("predicts", 0) + 1
            self._agg["predict_rows"] = \
                self._agg.get("predict_rows", 0) + int(rec.get("rows", 0))
            # cache counters arrive CUMULATIVE (the engine is process-
            # wide and predicts may run concurrently — per-call deltas
            # would steal each other's events); keep the latest
            cache = rec.get("cache") or {}
            for key in ("hits", "misses", "evictions"):
                if key in cache:
                    self._agg[f"predict_cache_{key}"] = float(cache[key])

    # ------------------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "backend": self._backend,
                "tier": self._tier,
                "duration_s": round(time.time() - self._t0, 3),
            }
            out.update({k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in self._agg.items()})
            if self._serve_lat:
                lat = sorted(self._serve_lat)
                out["serve_total_ms_p50"] = round(percentile(lat, 0.50), 3)
                out["serve_total_ms_p95"] = round(percentile(lat, 0.95), 3)
                out["serve_total_ms_p99"] = round(percentile(lat, 0.99), 3)
            if self._serve_occ_n:
                out["serve_mean_occupancy"] = round(
                    self._serve_occ_sum / self._serve_occ_n, 4)
            if self._explain_lat:
                lat = sorted(self._explain_lat)
                out["explain_total_ms_p50"] = \
                    round(percentile(lat, 0.50), 3)
                out["explain_total_ms_p95"] = \
                    round(percentile(lat, 0.95), 3)
                out["explain_total_ms_p99"] = \
                    round(percentile(lat, 0.99), 3)
            if self._router_lat:
                lat = sorted(self._router_lat)
                out["router_total_ms_p50"] = \
                    round(percentile(lat, 0.50), 3)
                out["router_total_ms_p95"] = \
                    round(percentile(lat, 0.95), 3)
                out["router_total_ms_p99"] = \
                    round(percentile(lat, 0.99), 3)
            if self._phase_totals:
                out["phase_totals_ms"] = {
                    k: round(v, 3) for k, v in sorted(
                        self._phase_totals.items(),
                        key=lambda kv: -kv[1])}
            return out

    def close(self, log: bool = True) -> None:
        """Emit ``run_end`` with the aggregate summary, Log.info it, and
        release the file handle.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            s = self.summary()
            self.emit("run_end", summary=s)
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None
        with _OPEN_LOCK:
            if self in _OPEN_RECORDERS:
                _OPEN_RECORDERS.remove(self)
        if log:
            parts = [f"telemetry: {s.get('iterations', 0):.0f} iterations"
                     if s.get("iterations") else "telemetry:"]
            if s.get("xla_compiles"):
                parts.append(f"{s['xla_compiles']:.0f} XLA compiles "
                             f"({s.get('xla_compile_secs', 0.0):.1f}s)")
            if s.get("predicts"):
                parts.append(
                    f"{s['predicts']:.0f} predicts "
                    f"({s.get('predict_cache_hits', 0):.0f} cache hits / "
                    f"{s.get('predict_cache_misses', 0):.0f} misses)")
            if s.get("ckpt_saves") or s.get("ckpt_loads"):
                parts.append(
                    f"{s.get('ckpt_saves', 0):.0f} checkpoints "
                    f"({s.get('ckpt_bytes', 0) / 1e6:.1f} MB, "
                    f"{s.get('ckpt_save_ms', 0.0):.0f} ms), "
                    f"{s.get('ckpt_loads', 0):.0f} loads, "
                    f"{s.get('ckpt_fallbacks', 0):.0f} fallbacks")
            if s.get("fleet_publishes") or s.get("fleet_restarts") or \
                    s.get("fleet_skips") or s.get("fleet_rollbacks"):
                parts.append(
                    f"fleet: {s.get('fleet_publishes', 0):.0f} "
                    f"publishes, {s.get('fleet_skips', 0):.0f} skips, "
                    f"{s.get('fleet_rollbacks', 0):.0f} rollbacks, "
                    f"{s.get('fleet_restarts', 0):.0f} restarts")
            if s.get("recovery_detects") or s.get("recovery_remeshes") \
                    or s.get("recovery_reshards"):
                parts.append(
                    f"elastic: {s.get('recovery_detects', 0):.0f} "
                    f"shard-failure detections, "
                    f"{s.get('recovery_remeshes', 0):.0f} re-meshes, "
                    f"{s.get('recovery_reshards', 0):.0f} resume "
                    f"re-shards, "
                    f"{s.get('recovery_escalations', 0):.0f} "
                    f"escalations")
            if s.get("continual_batches") or s.get("continual_quarantines"):
                parts.append(
                    f"continual: {s.get('continual_batches', 0):.0f} "
                    f"batches ({s.get('continual_rows', 0):.0f} rows), "
                    f"{s.get('continual_quarantines', 0):.0f} "
                    f"quarantined, "
                    f"{s.get('continual_stall_restarts', 0):.0f} stall "
                    f"restarts, {s.get('continual_nonfinite', 0):.0f} "
                    f"non-finite aborts")
            if s.get("serve_requests"):
                parts.append(
                    f"{s['serve_requests']:.0f} serve requests "
                    f"(p50 {s.get('serve_total_ms_p50', 0):.1f} / "
                    f"p99 {s.get('serve_total_ms_p99', 0):.1f} ms, "
                    f"{s.get('serve_shed', 0):.0f} shed, "
                    f"{s.get('serve_timeout', 0):.0f} timeout, "
                    f"{s.get('serve_rejected', 0):.0f} rejected)")
            if s.get("slo_evals"):
                parts.append(
                    f"slo: {s['slo_evals']:.0f} evals "
                    f"({s.get('slo_fast_burn', 0):.0f} fast-burn, "
                    f"{s.get('slo_slow_burn', 0):.0f} slow-burn, "
                    f"{s.get('slo_budget_exhausted', 0):.0f} "
                    f"budget-exhausted)")
            if s.get("autoscale_actions"):
                parts.append(
                    f"autoscale: {s['autoscale_actions']:.0f} actions "
                    f"({s.get('autoscale_grow', 0):.0f} grow, "
                    f"{s.get('autoscale_drain', 0):.0f} drain, "
                    f"{s.get('autoscale_retune_shed', 0):.0f} retune)")
            if s.get("captures"):
                parts.append(f"{s['captures']:.0f} flight-recorder "
                             f"capture(s)")
            if self.path:
                parts.append(f"records -> {self.path}")
            Log.info("%s", ", ".join(parts))
            for name, ms in list(
                    (s.get("phase_totals_ms") or {}).items())[:6]:
                Log.info("telemetry phase %-24s %10.1f ms", name, ms)


# ----------------------------------------------------------------------
# schema lint
# ----------------------------------------------------------------------
def validate_record(rec: Any) -> List[str]:
    """Schema-lint one record; returns a list of problems (empty =
    valid).  The contract ``tools/triage_run.py --check`` enforces."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return ["record is not a JSON object"]
    for field, ftype in _COMMON_FIELDS:
        if field not in rec:
            errs.append(f"missing field {field!r}")
            continue
        v = rec[field]
        # bool is an int subclass; numeric fields must be real numbers
        ok = isinstance(v, (int, float) if ftype is float else ftype) \
            and not isinstance(v, bool)
        if ftype is str:
            ok = isinstance(v, str)
        if not ok:
            errs.append(f"field {field!r} has type {type(v).__name__}")
    if errs:
        return errs
    if rec["schema"] != SCHEMA_VERSION:
        errs.append(f"schema version {rec['schema']} != {SCHEMA_VERSION}")
    rtype = rec["type"]
    if rtype not in RECORD_TYPES:
        errs.append(f"unknown record type {rtype!r}")
        return errs
    for field, ftype in _TYPE_FIELDS.get(rtype, ()):
        if field not in rec:
            errs.append(f"{rtype}: missing field {field!r}")
        elif field != "engine" and isinstance(rec[field], bool):
            errs.append(f"{rtype}: field {field!r} is bool")
        elif not isinstance(rec[field], ftype):
            errs.append(f"{rtype}: field {field!r} has type "
                        f"{type(rec[field]).__name__}")
    return errs


def read_records(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def lint_file(path: str) -> Tuple[int, List[str]]:
    """(record count, errors).  Errors carry 1-based line numbers."""
    n = 0
    errs: List[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            n += 1
            try:
                rec = json.loads(line)
            except ValueError as exc:
                errs.append(f"line {lineno}: not JSON ({exc})")
                continue
            for e in validate_record(rec):
                errs.append(f"line {lineno}: {e}")
    if n == 0:
        errs.append("no records")
    return n, errs


# ----------------------------------------------------------------------
# bench-artifact recovery parser (shared by bench.py and the tools)
# ----------------------------------------------------------------------
_BENCH_GLOB = "BENCH_r[0-9][0-9].json"


def _recover_json_line(text: str) -> Optional[Dict[str, Any]]:
    """Last parseable JSON object in ``text``.  Driver wrappers keep
    only the final bytes of stdout, so the last line's HEAD may be cut
    mid-key — recover by dropping everything before the first complete
    ``, "key":`` boundary and re-opening the object."""
    lines = [ln.strip() for ln in text.strip().splitlines()
             if ln.strip().endswith("}")]
    for line in reversed(lines):
        if not line.startswith("{"):
            cut = line.find(', "')
            if cut < 0:
                continue
            line = "{" + line[cut + 2:]
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def parse_bench_artifact(path: str) -> Optional[Dict[str, Any]]:
    """Parse one BENCH artifact into the bench's result dict.

    Accepts the driver wrapper form ``{"n", "cmd", "rc", "tail",
    "parsed"}`` (preferring ``parsed``, recovering from a truncated
    ``tail`` otherwise; ``rc != 0`` yields None) and the raw
    JSON-lines form ``bench.py`` itself prints.  A recovered dict must
    look like a bench result (carry a known bench key) — driver noise
    never becomes a benchmark row."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    obj = None
    try:
        obj = json.loads(text)
    except ValueError:
        pass
    if isinstance(obj, dict) and "tail" in obj and "rc" in obj:
        if obj.get("rc") != 0:
            return None
        parsed = obj.get("parsed")
        rec = parsed if isinstance(parsed, dict) \
            else _recover_json_line(str(obj.get("tail", "")))
    elif isinstance(obj, dict):
        rec = obj
    else:
        rec = _recover_json_line(text)
    if not isinstance(rec, dict):
        return None
    known = ("metric", "value", "vs_baseline", "iters_per_s",
             "tpu_unavailable")
    if not any(k in rec for k in known):
        return None
    return rec


def latest_good_bench(root: str) -> Tuple[Optional[str], Optional[Dict]]:
    """(artifact filename, parsed rows) of the NEWEST parseable bench
    artifact under ``root`` — outage rounds (rc != 0, unparseable, or
    ``tpu_unavailable`` re-emissions) are skipped."""
    for path in sorted(glob.glob(os.path.join(root, _BENCH_GLOB)),
                       reverse=True):
        rec = parse_bench_artifact(path)
        if rec is not None and not rec.get("tpu_unavailable"):
            return os.path.basename(path), rec
    return None, None


def bench_round(name: str) -> Optional[int]:
    m = re.match(r"BENCH_r(\d+)\.json$", os.path.basename(name))
    return int(m.group(1)) if m else None
