"""Numerical-health guard shared by every training path.

A batch whose labels or gradients go non-finite used to train straight
through to a silent NaN model that only the serve-time canary caught.
The guard lives at the points where leaf values are ALREADY host-side
(the per-tree record fetch, the fused block's packed fetch), so it
costs zero extra device calls:

- sequential / pipelined boosting: the materialized tree's leaf values
  are scanned right after ``_records_to_tree`` (``models/gbdt.py``);
- fused super-steps: a per-iteration finiteness flag is computed
  INSIDE the ``lax.scan`` (leaf values + updated score) and rides the
  existing stacked record fetch; on a bad iteration the block is
  exactly rewound to the served boundary (PR 3 rewind) before raising.

Detection raises :class:`NumericalHealthError` with iteration/phase
context and emits a ``continual`` telemetry record
(``event=nonfinite``).  One-shot ``engine.train`` fails loudly; the
continual daemon (``lightgbm_tpu/cont/``) catches it, quarantines the
offending batch, prunes its in-flight checkpoints and keeps training
from the pre-batch state.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["NumericalHealthError", "abort_nonfinite"]


class NumericalHealthError(RuntimeError):
    """Training produced non-finite leaf values or scores."""

    def __init__(self, iteration: int, phase: str, detail: str = ""):
        self.iteration = int(iteration)
        self.phase = str(phase)
        self.detail = str(detail)
        msg = (f"non-finite training state at iteration {iteration} "
               f"({phase})")
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


def abort_nonfinite(recorder, iteration: int, phase: str,
                    detail: str = "") -> None:
    """Emit the telemetry record + counter, log, and raise."""
    from . import telemetry as _telemetry
    from .log import Log
    _telemetry.counters.incr("nonfinite_aborts")
    rec = recorder if recorder is not None else _telemetry.get_recorder()
    if rec is not None:
        rec.emit("continual", event="nonfinite", iter=int(iteration),
                 phase=str(phase), detail=str(detail)[:200])
    Log.warning("numerical health: non-finite training state at "
                "iteration %d (%s)%s — aborting instead of training a "
                "NaN model", iteration, phase,
                f": {detail}" if detail else "")
    raise NumericalHealthError(iteration, phase, detail)
