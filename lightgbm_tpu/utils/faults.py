"""Unified deterministic fault-injection registry.

PR 5 proved a recovery path is only trustworthy once an injected fault
has actually exercised it (``LTPU_CKPT_FAULT``); this module
generalizes that env hook into ONE registry of named injection points
shared by every resilience layer — checkpoint writes/loads
(``ckpt/``), watcher snapshot validation (``serve/watcher.py``),
replica dispatch (``serve/server.py``), the HTTP front
(``serve/http.py``) and replica spawn (``serve/fleet.py``) — so tests
and CI chaos jobs drive crash/corruption/latency scenarios
deterministically instead of asserting recovery by hand.

Injection points (defined by their call sites; the registry itself is
point-agnostic):

=====================  =================================================
point                  modes its call site interprets
=====================  =================================================
``ckpt.save``          arms ONE whole checkpoint save (the hit counter
                       advances per save, preserving the PR 5
                       ``LTPU_CKPT_FAULT_AT`` semantics):
                       ``crash_blob`` / ``crash_manifest`` /
                       ``truncate_blob`` (``ckpt/atomic.py``)
``watcher.validate``   ``reject`` — the watcher treats the candidate
                       snapshot as manifest-invalid
``watcher.canary``     ``fail`` — canary scoring reports a mismatch
``serve.dispatch``     ``error`` — the batch dispatch raises (requests
                       finish with status ``error``); ``sleep_<ms>`` —
                       adds latency to every dispatch (p99 regression)
``serve.explain``      same modes, scoped to the explanation lane
                       only (``serve/server.py``) — predict batches
                       keep dispatching while explain degrades
``http.request``       ``error`` — the front answers a structured 500;
                       ``drop`` — the connection closes with no
                       response (client-visible transport failure)
``fleet.spawn``        ``fail`` — the replica spawn raises (exercises
                       restart backoff and the circuit breaker)
``ingest.read``        continual daemon batch read
                       (``cont/source.py``): ``error`` — the read
                       raises a TRANSIENT OSError (bounded exponential
                       backoff + retry); ``corrupt`` — the read raises
                       a non-transient parse error (the batch is
                       quarantined, reason ``read``)
``ingest.validate``    ``reject`` — the batch validation gate
                       (``cont/validate.py``) reports an injected
                       failure; the batch is quarantined
                       (reason ``validate``)
``trainer.step``       fired once per boosting iteration inside a
                       continual batch (``cont/trainer.py``):
                       ``error`` — the step raises (retry from the
                       last snapshot, then quarantine); ``hang`` — the
                       step blocks until abandoned (drives the stall
                       watchdog); ``sleep_<ms>`` — adds latency to the
                       step
``trainer.refit``      ``error`` — the continual refit pass raises
                       (retry from the last snapshot, then quarantine)
``mesh.collective``    fired once per fused-block dispatch of a
                       SHARDED super-step (``models/gbdt.py``):
                       ``error`` — the dispatch raises the way XLA
                       surfaces a dead peer (the elastic supervisor
                       classifies it as shard loss and re-meshes);
                       ``hang`` — the dispatch blocks the way a lost
                       shard stalls the collective rendezvous (drives
                       the collective-stall watchdog; blocks FOREVER
                       when unsupervised — faithful to the real
                       failure); ``sleep_<ms>`` — delays the dispatch
``mesh.heartbeat``     ``suppress`` — elastic per-block heartbeats are
                       dropped (a shard that stops reporting progress
                       without dying; combined with a dispatch delay
                       this trips the watchdog on a block that would
                       have landed)
``elastic.remesh``     ``error`` — one re-mesh attempt raises
                       (recovery degrades to a narrower survivor set,
                       bounded by ``elastic_min_shards``)
``router.backend``     fired once per FORWARDED routing attempt
                       (``serve/router.py``, primary and hedge alike):
                       ``sleep_<ms>`` — the attempt is delayed before
                       the backend sees it (injected brownout; the
                       hedge/retry machinery must make it invisible);
                       ``sleepb<i>_<ms>`` — the delay applies only
                       when the attempt targets backend index ``i``
                       of the route's URL order (ONE slow replica —
                       the hedging bench's brownout cell);
                       ``error`` — the attempt fails the way a dead
                       backend connection does (drives retry, backoff
                       and the per-backend circuit breaker)
``router.admit``       ``shed`` — the per-model admission budget
                       reports exhaustion for this request (a
                       structured 429 + Retry-After without having to
                       actually flood the token bucket)
``stream.chunk_read``  fired once per raw-chunk read of the streamed
                       ingest (``io/stream.py``, sample AND bin
                       passes): ``error`` — a TRANSIENT ``OSError``
                       (bounded exponential backoff + retry, then
                       quarantine); ``corrupt`` / ``truncate`` — a
                       deterministic parse failure (immediate
                       quarantine); ``hang`` — the read blocks;
                       ``sleep_<ms>`` — added latency
``stream.cache_write`` fired once per cache commit (prelude, each
                       chunk, manifest — ``io/cache.py``): ``error``
                       — the write raises ``OSError``; ``crash`` —
                       die mid-write with torn bytes on disk (the
                       SIGKILL shape: resume must reuse everything
                       already attested); ``truncate`` — publish
                       normally then tear bytes off the final range
                       (lost pages; sha256 verify-on-load must
                       catch); ``hang`` / ``sleep_<ms>``
``stream.prefetch``    fired once per host->device upload window
                       (``BlockFetcher``): ``error`` — window prep
                       raises (bounded retry, then fail loudly);
                       ``hang`` — the prefetch thread blocks (an
                       upload that never finishes); ``sleep_<ms>`` —
                       added latency (widens the overlap window the
                       telemetry measures)
``pager.fetch``        fired once per page prep of the device-block
                       pager (``io/pager.py``, serve path and
                       prefetch thread alike): ``error`` — the prep
                       raises ``OSError`` (surfaces through the
                       training callback — paged training fails
                       loudly, never silently drops a page);
                       ``crash`` — die mid-page-stream (the SIGKILL
                       shape: resume from the last checkpoint must be
                       byte-identical); ``sleep_<ms>`` — added prep
                       latency (widens/starves the prefetch overlap
                       the pager telemetry measures)
``pager.writeback``    fired once per page spill write (LRU eviction
                       to the pager's spill file): ``error`` — the
                       write-back is dropped (the page re-preps from
                       source later; costs time, never bytes);
                       ``crash`` — die mid-write-back
``pager.evict``        fired once per resident-page eviction:
                       ``crash`` — die at the eviction boundary
``slo.scrape``         fired once per SLO engine tick
                       (``obs/slo.py``): ``error`` — every objective
                       source scrape raises; the tick degrades to
                       ``status=scrape_error`` records on last-known
                       state (the engine never crashes its host)
``autoscale.decide``   fired once per autoscaler control step
                       (``serve/autoscaler.py``): ``error`` — the
                       step raises and degrades to a no-op
                       (``mode=degraded`` record; the fleet stays at
                       its current size); ``hang`` — the controller
                       wedges until stopped WITHOUT touching the
                       fleet (the chaos harness pins that serving
                       continues unsteered)
=====================  =================================================

A spec naming a point outside this table arms nothing — a typo'd
chaos spec would silently inject NOTHING — so the registry warns
(``Log`` + the ``faults_unknown_point`` telemetry counter + a
``continual`` record when a recorder is live) the first time each
unknown point is configured, armed or read from ``LTPU_FAULTS``.

Spec syntax (``LTPU_FAULTS`` env var or :func:`configure`), comma
separated::

    point:mode          fire on the 1st hit of ``point`` only
    point:mode@4        fire on the 4th hit only
    point:mode@4+       fire on every hit from the 4th on
    point:mode@*        fire on every hit

Hits are counted per point, process-wide, under a lock — the n-th hit
is the n-th call to :func:`fire` for that point, whatever thread makes
it — so a spec names ONE deterministic event in the process's
execution, not a probability.  The legacy ``LTPU_CKPT_FAULT`` /
``LTPU_CKPT_FAULT_AT`` env pair keeps working: it is folded in as
``ckpt.save:<mode>@<at>``.

Remote driving: with ``serve_debug_faults=true`` the HTTP front
exposes ``POST /faults {"spec": ...}`` / ``GET /faults``, so a chaos
harness (``tools/loadgen_serve.py --fleet``) can arm dispatch faults
inside live replica processes.  The endpoint is OFF by default.

``InjectedFault`` deliberately subclasses ``BaseException``: cleanup
paths guarded by ``except Exception`` must NOT swallow it (a real
SIGKILL would not run them either).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["InjectedFault", "FaultSpec", "KNOWN_POINTS", "configure",
           "arm", "clear", "reset", "fire", "hits", "snapshot",
           "parse_specs", "active_spec"]

# the registered injection points (the table above).  The registry
# itself stays point-agnostic — this set only powers the typo warning.
KNOWN_POINTS = frozenset({
    "ckpt.save", "watcher.validate", "watcher.canary", "serve.dispatch",
    "serve.explain", "http.request", "fleet.spawn", "ingest.read",
    "ingest.validate",
    "trainer.step", "trainer.refit", "mesh.collective",
    "mesh.heartbeat", "elastic.remesh", "router.backend",
    "router.admit", "stream.chunk_read", "stream.cache_write",
    "stream.prefetch", "slo.scrape", "autoscale.decide",
    "pager.fetch", "pager.writeback", "pager.evict",
})


class InjectedFault(BaseException):
    """Simulated crash raised at an injection point (tests/CI only)."""


class FaultSpec:
    """One parsed ``point:mode@ordinal`` spec."""

    __slots__ = ("point", "mode", "start", "open_ended")

    def __init__(self, point: str, mode: str, start: int = 1,
                 open_ended: bool = False):
        self.point = str(point)
        self.mode = str(mode)
        self.start = max(int(start), 1)
        self.open_ended = bool(open_ended)

    def matches(self, hit: int) -> bool:
        return hit >= self.start if self.open_ended else hit == self.start

    def __repr__(self) -> str:
        at = "*" if (self.open_ended and self.start == 1) else (
            f"{self.start}+" if self.open_ended else str(self.start))
        return f"{self.point}:{self.mode}@{at}"


def parse_specs(text: str) -> List[FaultSpec]:
    """Parse a comma-separated spec string; raises ValueError on a
    malformed entry (a typo'd chaos spec must fail loudly, not inject
    nothing)."""
    out: List[FaultSpec] = []
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"fault spec {part!r}: expected point:mode")
        point, rest = part.split(":", 1)
        mode, at = rest, "1"
        if "@" in rest:
            mode, at = rest.rsplit("@", 1)
        if not point.strip() or not mode.strip():
            raise ValueError(f"fault spec {part!r}: empty point or mode")
        at = at.strip()
        if at == "*":
            out.append(FaultSpec(point.strip(), mode.strip(), 1, True))
        elif at.endswith("+"):
            out.append(FaultSpec(point.strip(), mode.strip(),
                                 int(at[:-1]), True))
        else:
            out.append(FaultSpec(point.strip(), mode.strip(), int(at)))
    return out


class FaultRegistry:
    """Process-wide registry: programmatic specs + env specs + the
    legacy checkpoint env pair, with per-point hit counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._specs: List[FaultSpec] = []
        # env parse cache: (raw string) -> parsed list
        self._env_cache: Tuple[str, List[FaultSpec]] = ("", [])
        self._legacy_cache: Tuple[Tuple[str, str], List[FaultSpec]] = \
            (("", ""), [])
        self._warned_points: set = set()

    def _warn_unknown(self, specs: List[FaultSpec],
                      source: str) -> None:
        """Log + telemetry for specs naming an unregistered point — a
        typo'd point arms NOTHING, which a chaos job must not discover
        by its scenario silently passing.  Once per point."""
        for spec in specs:
            if spec.point in KNOWN_POINTS:
                continue
            with self._lock:
                if spec.point in self._warned_points:
                    continue
                self._warned_points.add(spec.point)
            from .log import Log
            from . import telemetry as _telemetry
            Log.warning("faults: %s names unregistered point %r — no "
                        "call site fires it, so this spec injects "
                        "NOTHING (known points: %s)", source,
                        spec.point, ", ".join(sorted(KNOWN_POINTS)))
            _telemetry.counters.incr("faults_unknown_point")
            rec = _telemetry.get_recorder()
            if rec is not None:
                rec.emit("continual", event="fault_unknown_point",
                         point=spec.point, source=source)

    # -- configuration -------------------------------------------------
    def configure(self, spec: str) -> List[FaultSpec]:
        """Replace the programmatic specs with ``spec`` (empty string
        clears them).  Hit counters are NOT reset — an already-burned
        ordinal stays burned unless :meth:`reset` is called."""
        parsed = parse_specs(spec)
        with self._lock:
            self._specs = parsed
        self._warn_unknown(parsed, "configure()")
        return parsed

    def arm(self, point: str, mode: str, at: str = "1") -> None:
        """Append one programmatic spec (``at`` as in the spec syntax:
        ``"3"``, ``"3+"`` or ``"*"``)."""
        spec = parse_specs(f"{point}:{mode}@{at}")[0]
        with self._lock:
            self._specs.append(spec)
        self._warn_unknown([spec], "arm()")

    def clear(self) -> None:
        with self._lock:
            self._specs = []

    def reset(self, point: Optional[str] = None) -> None:
        """Reset hit counters (one point, or all)."""
        with self._lock:
            if point is None:
                self._hits = {}
            else:
                self._hits.pop(point, None)

    # -- env merging ---------------------------------------------------
    def _env_specs(self) -> List[FaultSpec]:
        raw = os.environ.get("LTPU_FAULTS", "")
        if raw != self._env_cache[0]:
            try:
                parsed = parse_specs(raw)
            except ValueError:
                from .log import Log
                Log.warning("faults: ignoring malformed LTPU_FAULTS=%r",
                            raw)
                parsed = []
            self._env_cache = (raw, parsed)
            self._warn_unknown(parsed, f"LTPU_FAULTS={raw!r}")
        return self._env_cache[1]

    def _legacy_specs(self) -> List[FaultSpec]:
        mode = os.environ.get("LTPU_CKPT_FAULT", "")
        at = os.environ.get("LTPU_CKPT_FAULT_AT", "1") or "1"
        if not mode:
            return []
        if (mode, at) != self._legacy_cache[0]:
            try:
                parsed = [FaultSpec("ckpt.save", mode, int(at))]
            except ValueError:
                parsed = [FaultSpec("ckpt.save", mode, 1)]
            self._legacy_cache = ((mode, at), parsed)
        return self._legacy_cache[1]

    # -- firing --------------------------------------------------------
    def fire(self, point: str) -> str:
        """Advance ``point``'s hit counter and return the armed mode
        for THIS hit, or ``''``.  First matching spec wins
        (programmatic before env before legacy)."""
        with self._lock:
            self._hits[point] = self._hits.get(point, 0) + 1
            n = self._hits[point]
            specs = list(self._specs)
        for spec in specs + self._env_specs() + self._legacy_specs():
            if spec.point == point and spec.matches(n):
                return spec.mode
        return ""

    def active_spec(self, point: str) -> Optional[FaultSpec]:
        """The first spec registered for ``point`` (introspection —
        does not advance the counter)."""
        with self._lock:
            specs = list(self._specs)
        for spec in specs + self._env_specs() + self._legacy_specs():
            if spec.point == point:
                return spec
        return None

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"hits": dict(self._hits),
                    "specs": [repr(s) for s in self._specs],
                    "env": os.environ.get("LTPU_FAULTS", ""),
                    "legacy": os.environ.get("LTPU_CKPT_FAULT", "")}


_REGISTRY = FaultRegistry()

configure = _REGISTRY.configure
arm = _REGISTRY.arm
clear = _REGISTRY.clear
reset = _REGISTRY.reset
fire = _REGISTRY.fire
hits = _REGISTRY.hits
snapshot = _REGISTRY.snapshot
active_spec = _REGISTRY.active_spec
