"""Single-sourced parameter registry.

The reference keeps all 258 parameters as structured comments in
``include/LightGBM/config.h`` which a generator compiles into an alias map +
setters (``src/io/config_auto.cpp``) and docs.  Here the registry is a list of
:class:`Param` descriptors from which the :class:`Config` dataclass, the alias
table and the docs are all derived — same single-source pattern, Python-first.

Parameter names, defaults and alias sets follow the reference
(``config.h:126-770``, ``config_auto.cpp:4``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from .utils.log import Log

__all__ = ["Param", "PARAMS", "ALIAS_TABLE", "Config", "param_docs"]


@dataclasses.dataclass(frozen=True)
class Param:
    name: str
    default: Any
    type: type
    aliases: Tuple[str, ...] = ()
    desc: str = ""
    group: str = "core"
    check: Optional[str] = None  # human-readable constraint, validated loosely


def _p(name, default, type_, aliases=(), desc="", group="core", check=None):
    return Param(name, default, type_, tuple(aliases), desc, group, check)


# ---------------------------------------------------------------------------
# The registry.  Grouping mirrors config.h: core / learning / io / objective /
# metric / network / device.
# ---------------------------------------------------------------------------
PARAMS: List[Param] = [
    # ---- core ----
    _p("config", "", str, ("config_file",), "path to config file"),
    _p("task", "train", str, ("task_type",),
       "train, predict, convert_model, refit, serve, continual, sweep"),
    _p("objective", "regression", str,
       ("objective_type", "app", "application", "loss"),
       "regression, regression_l1, huber, fair, poisson, quantile, mape, "
       "gamma, tweedie, binary, multiclass, multiclassova, cross_entropy, "
       "cross_entropy_lambda, lambdarank, rank_xendcg"),
    _p("boosting", "gbdt", str, ("boosting_type", "boost"),
       "gbdt, rf, dart, goss, mvs"),
    _p("data", "", str, ("train", "train_data", "train_data_file", "data_filename"),
       "path of training data"),
    _p("valid", "", str, ("test", "valid_data", "valid_data_file", "test_data",
                          "test_data_file", "valid_filenames"),
       "comma-separated validation data paths"),
    _p("num_iterations", 100, int,
       ("num_iteration", "n_iter", "num_tree", "num_trees", "num_round",
        "num_rounds", "num_boost_round", "n_estimators", "max_iter"),
       "number of boosting iterations", check=">=0"),
    _p("learning_rate", 0.1, float, ("shrinkage_rate", "eta"),
       "shrinkage rate", check=">0"),
    _p("num_leaves", 31, int, ("num_leaf", "max_leaves", "max_leaf",
                               "max_leaf_nodes"),
       "max number of leaves in one tree", check=">1"),
    _p("tree_learner", "serial", str,
       ("tree", "tree_type", "tree_learner_type"),
       "serial, feature, data, voting, data2d.  Parallel learners run "
       "SPMD over a 1-D device mesh (all devices, capped by "
       "num_machines; or an explicit mesh= keyword) with the strategy "
       "collectives in-program, and with fused_iters>1 the sharded "
       "build rides inside the fused lax.scan super-step; data2d "
       "shards rows x feature tiles over a 2-D (data, feature) mesh "
       "(mesh_shape) with per-axis collectives — see "
       "docs/Distributed.md"),
    _p("mesh_shape", "", str, (),
       "tree_learner=data2d: the 2-D device mesh as 'RxF' (rows x "
       "feature tiles, e.g. '4x2' or '4,2'); '' = factor the device "
       "count automatically (largest feature-axis divisor <= sqrt(D))",
       group="network"),
    _p("num_threads", 0, int, ("num_thread", "nthread", "nthreads", "n_jobs"),
       "number of host threads (0 = default)"),
    _p("device_type", "tpu", str, ("device",), "tpu, cpu (XLA backend)",
       group="device"),
    _p("seed", None, object, ("random_seed", "random_state"),
       "master seed, overridden by specific seeds"),
    # ---- learning control ----
    _p("max_depth", -1, int, (), "max tree depth, <=0 means no limit",
       group="learning"),
    _p("min_data_in_leaf", 20, int,
       ("min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf"),
       "minimal data in one leaf", group="learning", check=">=0"),
    _p("min_sum_hessian_in_leaf", 1e-3, float,
       ("min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian",
        "min_child_weight"),
       "minimal sum of hessians in one leaf", group="learning", check=">=0"),
    _p("bagging_fraction", 1.0, float, ("sub_row", "subsample", "bagging"),
       "row subsample fraction, used when bagging_freq>0", group="learning",
       check="0<x<=1"),
    _p("pos_bagging_fraction", 1.0, float,
       ("pos_sub_row", "pos_subsample", "pos_bagging"),
       "positive-class bagging fraction (binary)", group="learning"),
    _p("neg_bagging_fraction", 1.0, float,
       ("neg_sub_row", "neg_subsample", "neg_bagging"),
       "negative-class bagging fraction (binary)", group="learning"),
    _p("bagging_freq", 0, int, ("subsample_freq",),
       "perform bagging every k iterations", group="learning"),
    _p("bagging_seed", 3, int, ("bagging_fraction_seed",),
       "bagging random seed", group="learning"),
    _p("feature_fraction", 1.0, float,
       ("sub_feature", "colsample_bytree"),
       "per-tree feature subsample fraction", group="learning", check="0<x<=1"),
    _p("feature_fraction_seed", 2, int, (), "feature_fraction seed",
       group="learning"),
    _p("early_stopping_round", 0, int,
       ("early_stopping_rounds", "early_stopping", "n_iter_no_change"),
       "stop if one validation metric does not improve in this many rounds",
       group="learning"),
    _p("first_metric_only", False, bool, (),
       "only use the first metric for early stopping", group="learning"),
    _p("max_delta_step", 0.0, float, ("max_tree_output", "max_leaf_output"),
       "limit of leaf output, <=0 means no constraint", group="learning"),
    _p("lambda_l1", 0.0, float, ("reg_alpha",), "L1 regularization",
       group="learning", check=">=0"),
    _p("lambda_l2", 0.0, float, ("reg_lambda", "lambda"),
       "L2 regularization", group="learning", check=">=0"),
    _p("min_gain_to_split", 0.0, float, ("min_split_gain",),
       "minimal gain to perform split", group="learning", check=">=0"),
    _p("drop_rate", 0.1, float, ("rate_drop",), "DART dropout rate",
       group="learning"),
    _p("max_drop", 50, int, (), "DART max dropped trees per iteration",
       group="learning"),
    _p("skip_drop", 0.5, float, (), "DART probability of skipping drop",
       group="learning"),
    _p("xgboost_dart_mode", False, bool, (), "use xgboost dart normalization",
       group="learning"),
    _p("uniform_drop", False, bool, (), "DART uniform drop", group="learning"),
    _p("drop_seed", 4, int, (), "DART drop seed", group="learning"),
    _p("top_rate", 0.2, float, (), "GOSS large-gradient retain ratio",
       group="learning"),
    _p("other_rate", 0.1, float, (), "GOSS small-gradient sample ratio",
       group="learning"),
    _p("min_data_per_group", 100, int, (),
       "minimal data per categorical group", group="learning"),
    _p("max_cat_threshold", 32, int, (),
       "max categories in many-vs-many split set", group="learning"),
    _p("cat_l2", 10.0, float, (), "L2 in categorical split", group="learning"),
    _p("cat_smooth", 10.0, float, (),
       "smoothing for categorical bin sort", group="learning"),
    _p("max_cat_to_onehot", 4, int, (),
       "use one-vs-other when #categories <= this", group="learning"),
    _p("top_k", 20, int, ("topk",),
       "top-k features in voting parallel", group="learning"),
    _p("monotone_constraints", [], list,
       ("mc", "monotone_constraint"),
       "per-feature monotone constraints (-1,0,1)", group="learning"),
    _p("feature_contri", [], list, ("feature_contrib", "fc", "fp",
                                    "feature_penalty"),
       "per-feature split-gain multipliers", group="learning"),
    _p("forcedsplits_filename", "", str,
       ("fs", "forced_splits_filename", "forced_splits_file", "forced_splits"),
       "path to forced-splits JSON", group="learning"),
    _p("refit_decay_rate", 0.9, float, (),
       "leaf decay rate in refit task", group="learning"),
    _p("verbosity", 1, int, ("verbose",), "<0 fatal, 0 warn, 1 info, >1 debug"),
    # ---- io / dataset ----
    _p("max_bin", 255, int, (), "max number of bins per feature", group="io",
       check=">1"),
    _p("min_data_in_bin", 3, int, (), "minimal data inside one bin",
       group="io", check=">0"),
    _p("bin_construct_sample_cnt", 200000, int, ("subsample_for_bin",),
       "number of rows sampled to construct bins", group="io"),
    _p("histogram_pool_size", -1.0, float, ("hist_pool_size",),
       "max cache size (MB) for historical histograms, <0 = no limit",
       group="io"),
    _p("data_random_seed", 1, int, ("data_seed",),
       "seed for data partition in parallel learning", group="io"),
    _p("output_model", "LightGBM_model.txt", str,
       ("model_output", "model_out"), "output model filename", group="io"),
    _p("snapshot_freq", -1, int, ("save_period",),
       "snapshot cadence in iterations: with checkpoint_dir set, a "
       "full training checkpoint (lightgbm_tpu/ckpt/, resumable "
       "bit-exactly) is written every k iterations; without it, the "
       "CLI falls back to the reference's model-text snapshots "
       "(<output_model>.snapshot_iter_k).  <=0 disables periodic "
       "snapshots (a final/preemption checkpoint is still written "
       "when checkpoint_dir is set)", group="io"),
    _p("checkpoint_dir", "", str, ("ckpt_dir", "checkpoint_path"),
       "root directory for fault-tolerant training checkpoints "
       "(docs/Checkpointing.md): atomic temp+fsync+rename snapshot "
       "directories carrying the complete training state (tree "
       "tables, score carries, PRNG streams, bagging-cycle position, "
       "early-stopping state) with a content-hashed manifest; "
       "enables the now-live snapshot_freq cadence, a SIGTERM/SIGINT "
       "best-effort final checkpoint, and resume_from; '' disables "
       "checkpointing", group="io"),
    _p("keep_last_n", 2, int, ("checkpoint_keep_last_n", "keep_last"),
       "checkpoint retention: only the newest n valid checkpoints "
       "survive each save (older directories are pruned)",
       group="io", check=">=1"),
    _p("resume_from", "", str, ("resume", "resume_checkpoint"),
       "resume training from a checkpoint: a finalized ckpt_* "
       "directory, a checkpoint root (newest VALID snapshot wins, "
       "falling back past corrupt/truncated ones), or 'auto'/'latest' "
       "to discover inside checkpoint_dir (starting fresh when none "
       "exists yet — the preemptible-fleet loop's idempotent form).  "
       "The continuation is bit-exact: trees, scores and RNG streams "
       "match the uninterrupted run", group="io"),
    _p("input_model", "", str, ("model_input", "model_in"),
       "input model path (continue train / predict)", group="io"),
    _p("output_result", "LightGBM_predict_result.txt", str,
       ("predict_result", "prediction_result", "predict_name",
        "prediction_name", "pred_name", "name_pred"),
       "prediction output file", group="io"),
    _p("initscore_filename", "", str,
       ("init_score_filename", "init_score_file", "init_score",
        "input_init_score"),
       "initial score file path", group="io"),
    _p("valid_data_initscores", "", str,
       ("valid_data_init_scores", "valid_init_score_file", "valid_init_score"),
       "comma-separated init score files for validation data", group="io"),
    _p("pre_partition", False, bool, ("is_pre_partition",),
       "data is pre-partitioned across machines", group="io"),
    _p("enable_bundle", True, bool, ("is_enable_bundle", "bundle"),
       "enable exclusive feature bundling", group="io"),
    _p("max_conflict_rate", 0.0, float, (),
       "max conflict rate in EFB", group="io"),
    _p("is_enable_sparse", True, bool, ("is_sparse", "enable_sparse", "sparse"),
       "enable sparse optimization", group="io"),
    _p("sparse_threshold", 0.8, float, (),
       "sparsity threshold for sparse bin storage", group="io"),
    _p("use_missing", True, bool, (), "enable missing value handling",
       group="io"),
    _p("zero_as_missing", False, bool, (),
       "treat zero as missing", group="io"),
    _p("two_round", False, bool,
       ("two_round_loading", "use_two_round_loading"),
       "two-round data loading (low memory)", group="io"),
    # ---- out-of-core streaming ingest (io/stream.py, io/cache.py,
    # docs/Streaming.md) ----
    _p("stream_ingest", False, bool, ("stream", "out_of_core"),
       "out-of-core streamed ingest (docs/Streaming.md): the raw "
       "matrix is read chunk-by-chunk (ndarray, <stem>.X.npy mmap "
       "pair, or a directory of npz shards), bin mappers are fit once "
       "from a single streamed sample pass, and the binned matrix is "
       "published to a crash-safe content-keyed mmap cache under "
       "stream_cache_dir (per-chunk sha256 attestations, manifest "
       "LAST) that training uploads in budgeted double-buffered "
       "host->device windows.  The trained model is byte-identical "
       "to the in-memory path; a SIGKILL mid-ingest resumes without "
       "re-fitting a mapper or re-binning a published chunk, and a "
       "corrupt/truncated chunk is re-binned ALONE", group="io"),
    _p("stream_cache_dir", "", str, ("stream_cache", "ingest_cache_dir"),
       "root directory for the crash-safe binned dataset cache "
       "(required when stream_ingest=true).  One content-keyed "
       "subdirectory per (source, binning config) pair; checkpoint "
       "manifests record the cache identity so resume reuses the "
       "cache instead of re-binning (a miss is a MED anomaly)",
       group="io"),
    _p("stream_chunk_rows", 0, int, ("ingest_chunk_rows",),
       "rows per streamed ingest chunk (the unit of crash-safe "
       "publish and single-chunk repair).  0 sizes chunks from "
       "stream_host_budget_mb; explicit values above the budget are "
       "clamped with an ingest/clamp telemetry record (graceful "
       "degradation instead of an OOM kill)", group="io", check=">=0"),
    _p("stream_host_budget_mb", 256, int, ("stream_budget_mb",),
       "host staging budget for streamed ingest and the host->device "
       "upload windows: no raw chunk, binned window or in-flight "
       "transfer buffer exceeds this working-set bound — larger "
       "datasets degrade to smaller chunk windows, never to an OOM "
       "kill", group="io", check=">=1"),
    _p("stream_window_rows", 0, int, (),
       "rows per host->device upload window of the streamed "
       "construction (the double-buffered BlockFetcher unit).  0 "
       "sizes windows from stream_host_budget_mb; explicit values "
       "above the budget are clamped like stream_chunk_rows",
       group="io", check=">=0"),
    _p("stream_read_retries", 3, int, (),
       "bounded retries for TRANSIENT raw-chunk read failures under "
       "exponential backoff (the cont/source.py policy, shared); "
       "exhausted retries quarantine the chunk (HIGH anomaly) and "
       "ingest fails loudly after binning every other chunk",
       group="io", check=">=0"),
    _p("stream_backoff_base_s", 0.1, float, (),
       "base of the streamed-ingest exponential read backoff",
       group="io", check=">=0"),
    _p("stream_prefetch", True, bool, (),
       "double-buffer the host->device upload windows: a prefetch "
       "thread prepares window i+1 (mmap page-in, transpose, pad, "
       "EFB transform) while window i's async device copy runs.  "
       "~zero measured overlap with streaming enabled is a MED "
       "anomaly (obs/rules.py)", group="io"),
    # ---- device-block pager: out-of-core ON DEVICE (io/pager.py,
    # docs/Streaming.md "Out-of-core on device") ----
    _p("paged_training", "auto", str, ("paged",),
       "device-block paged training (docs/Streaming.md): the (F, N) "
       "binned matrix never materializes in device memory — each "
       "shard's row range splits into fixed-size row pages served "
       "from the binned cache, and the per-iteration histogram pass "
       "becomes a page loop whose page p+1 prefetch rides under page "
       "p's compute.  'auto' pages only when the per-device matrix "
       "exceeds hbm_budget_mb; 'on' forces paging (ValueError if the "
       "config is paged-ineligible: requires the baseline "
       "hist_impl=segsum / split_kernel=xla lane, no wave growth or "
       "speculation); 'off' always trains resident.  Paged models "
       "are byte-identical to resident ones (tests/test_pager.py)",
       group="io", check="auto, on, off"),
    _p("hbm_budget_mb", 0.0, float, ("device_budget_mb",),
       "per-device memory budget for the PAGED binned matrix (the "
       "page double-buffer): with paged_training=auto, paging "
       "activates when a device's resident matrix block would exceed "
       "this many MB, and the page size is chosen so two page slots "
       "fit inside it.  0 disables the auto trigger", group="io",
       check=">=0"),
    _p("paged_page_rows", 0, int, (),
       "explicit rows per page of the device-block pager (overrides "
       "the hbm_budget_mb-derived page size; mainly for tests and "
       "benchmarks pinning a page count).  0 derives the size from "
       "the budget", group="io", check=">=0"),
    _p("save_binary", False, bool, ("is_save_binary", "is_save_binary_file"),
       "save dataset to binary file", group="io"),
    _p("header", False, bool, ("has_header",), "input data has header",
       group="io"),
    _p("label_column", "", str, ("label",), "label column (index or name:)",
       group="io"),
    _p("weight_column", "", str, ("weight",), "weight column", group="io"),
    _p("group_column", "", str,
       ("group", "group_id", "query_column", "query", "query_id"),
       "query/group column for ranking", group="io"),
    _p("ignore_column", "", str, ("ignore_feature", "blacklist"),
       "columns to ignore", group="io"),
    _p("categorical_feature", "", object,
       ("cat_feature", "categorical_column", "cat_column"),
       "categorical features (indices or name: list)", group="io"),
    _p("predict_raw_score", False, bool,
       ("is_predict_raw_score", "predict_rawscore", "raw_score"),
       "predict raw scores", group="io"),
    _p("predict_leaf_index", False, bool,
       ("is_predict_leaf_index", "leaf_index"),
       "predict leaf indices", group="io"),
    _p("predict_contrib", False, bool, ("is_predict_contrib", "contrib"),
       "predict SHAP feature contributions", group="io"),
    _p("num_iteration_predict", -1, int, (),
       "number of iterations used in prediction", group="io"),
    _p("pred_early_stop", False, bool, (), "use early stopping in prediction",
       group="io"),
    _p("pred_early_stop_freq", 10, int, (), "prediction early stop frequency",
       group="io"),
    _p("pred_early_stop_margin", 10.0, float, (),
       "prediction early stop margin", group="io"),
    _p("predict_engine", True, bool, ("use_predict_engine",),
       "serve predict/predict_raw/predict_leaf_index from the "
       "ensemble-flattened jitted batch engine (ops/predict.py); "
       "false = per-tree host traversal", group="io"),
    _p("predict_chunk_rows", 16384, int, (),
       "row-chunk size of the batched inference engine; chunks are "
       "padded to power-of-two buckets that key the compile cache",
       group="io", check=">0"),
    _p("predict_cache_slots", 16, int, ("predict_cache_size",),
       "capacity of the inference engine's compiled-kernel LRU "
       "(ops/predict.py).  One slot holds the jitted predictors for "
       "one (row bucket, tree layout) shape; serving a wider shape "
       "mix than this thrashes the cache (visible as "
       "predict_cache_evictions in telemetry and triage_run.py).  "
       "The engine is process-wide, so the last booster to predict "
       "wins; inspect with Booster.predict_cache_info()",
       group="io", check=">0"),
    _p("telemetry_file", "", str, ("telemetry", "telemetry_filename"),
       "append schema-versioned JSONL run records to this path: "
       "per-iteration phase timings, XLA compile/retrace counters, "
       "predict-engine cache hits/misses/evictions, histogram tier/gate "
       "decisions, collective payload bytes, backend identity; '' "
       "disables.  Read with tools/triage_run.py (anomaly triage, "
       "--check schema lint); a summary is logged at shutdown",
       group="io"),
    _p("convert_model_language", "", str, (),
       "language of converted model (cpp)", group="io"),
    _p("convert_model", "gbdt_prediction.cpp", str,
       ("convert_model_file",), "converted model output", group="io"),
    # ---- objective ----
    _p("num_class", 1, int, ("num_classes",), "number of classes (multiclass)",
       group="objective", check=">0"),
    _p("is_unbalance", False, bool, ("unbalance", "unbalanced_sets"),
       "unbalanced binary training data", group="objective"),
    _p("scale_pos_weight", 1.0, float, (), "weight of positive class",
       group="objective", check=">0"),
    _p("sigmoid", 1.0, float, (), "sigmoid scaling parameter",
       group="objective", check=">0"),
    _p("boost_from_average", True, bool, (),
       "initialize score from average label", group="objective"),
    _p("reg_sqrt", False, bool, (), "fit sqrt(label) for regression_l2",
       group="objective"),
    _p("alpha", 0.9, float, (), "huber/quantile alpha", group="objective",
       check=">0"),
    _p("fair_c", 1.0, float, (), "fair loss parameter", group="objective",
       check=">0"),
    _p("poisson_max_delta_step", 0.7, float, (),
       "poisson safeguard parameter", group="objective", check=">0"),
    _p("tweedie_variance_power", 1.5, float, (),
       "tweedie variance power in [1,2)", group="objective"),
    _p("max_position", 20, int, (), "NDCG optimization position (lambdarank)",
       group="objective", check=">0"),
    _p("lambdamart_norm", True, bool, ("lambdarank_norm",),
       "normalize lambdas in lambdarank", group="objective"),
    _p("label_gain", [], list, (), "gain per label level in lambdarank",
       group="objective"),
    _p("var_weight", 1e-6, float, (),
       "regularizer inside the MVS sampling score "
       "sqrt((sum|g*h|)^2 + var_weight)", group="objective"),
    # ---- metric ----
    _p("metric", "", object,
       ("metrics", "metric_types"),
       "metric names, comma-separated; '' = from objective, 'None' = none",
       group="metric"),
    _p("metric_freq", 1, int, ("output_freq",), "metric output frequency",
       group="metric", check=">0"),
    _p("is_provide_training_metric", False, bool,
       ("training_metric", "is_training_metric", "train_metric"),
       "output metrics on training data", group="metric"),
    _p("eval_at", [1, 2, 3, 4, 5], list,
       ("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at"),
       "positions for ndcg/map evaluation", group="metric"),
    _p("multi_error_top_k", 1, int, (), "top-k threshold for multi_error",
       group="metric"),
    # ---- network ----
    _p("num_machines", 1, int, ("num_machine",),
       "number of machines in distributed training", group="network",
       check=">0"),
    _p("local_listen_port", 12400, int, ("local_port",),
       "listening port (socket backend analog)", group="network"),
    _p("time_out", 120, int, (), "socket timeout in minutes", group="network"),
    _p("machine_list_filename", "", str,
       ("machine_list_file", "machine_list", "mlist"),
       "machine list file", group="network"),
    _p("machines", "", str, ("workers", "nodes"),
       "comma-separated machine list", group="network"),
    # ---- elastic (shard-loss recovery for sharded training) ----
    _p("elastic_training", False, bool, ("elastic",),
       "supervise mesh-sharded fused training (tree_learner="
       "data/feature/voting/data2d with fused_iters>1) for shard "
       "loss: each fused-block dispatch runs under a collective-stall "
       "watchdog and a per-block heartbeat; a failed or hung shard "
       "triggers exact rewind to the served boundary, a re-mesh over "
       "the surviving devices (a 2-D mesh drops the full row or "
       "column that loses fewer devices), and bit-exact continuation "
       "— see docs/Distributed.md", group="elastic"),
    _p("elastic_stall_timeout_s", 120.0, float, (),
       "collective-stall watchdog: a fused-block dispatch silent this "
       "long (no heartbeat) is abandoned as a hung collective and "
       "recovery runs; a mesh identity's first block gets a 5x "
       "compile grace; <=0 disables the watchdog (failures are still "
       "detected as exceptions)", group="elastic", check=""),
    _p("elastic_max_remesh", 2, int, (),
       "shard-loss recoveries (re-meshes) one run may spend before "
       "escalating with ElasticError (restart from checkpoint owns "
       "anything past this)", group="elastic", check=">=0"),
    _p("elastic_min_shards", 1, int, (),
       "below this surviving mesh width recovery escalates instead "
       "of degrading further (1 permits the serial-learner fallback)",
       group="elastic", check=">=1"),
    # ---- device ----
    _p("gpu_platform_id", -1, int, (), "(compat) OpenCL platform id",
       group="device"),
    _p("gpu_device_id", -1, int, (), "(compat) device id", group="device"),
    _p("gpu_use_dp", False, bool, (),
       "use float64 accumulation in device histograms", group="device"),
    _p("tpu_rows_per_block", 16384, int, (),
       "row-padding quantum / max rows per Pallas histogram block",
       group="device"),
    _p("use_quantized_grad", False, bool, ("quantized_grad",),
       "histogram gradients/hessians as stochastically-rounded small "
       "integers: exact in bf16, so the speculative histogram pass packs "
       "42 leaves per MXU matmul instead of 21 (device learner only).  "
       "Under wave growth, eligible configs (min_data_in_leaf <= 1, "
       "min_sum_hessian_in_leaf > 0, no categorical features, no EFB "
       "bundles) drop further to two-column (grad, hess) passes fitting "
       "64 leaves per pass: the histogram count channel becomes a "
       "QUANTIZED HESS COPY.  Missing-value caveat of that proxy: the "
       "default-direction \"any missing data here?\" test reads the "
       "hess-copy channel instead of a real count, so a missing-bin row "
       "whose quantized hessian rounds to 0 is treated as absent for "
       "the direction choice ONLY (both directions tie in gain there; "
       "split thresholds and leaf values are unaffected, and real leaf "
       "counts are restored from the full-precision renewal sums — "
       "quality is pinned by the NaN-injection oracle test).  Set "
       "min_data_in_leaf >= 2 to force the counted W=42 tier instead",
       group="device"),
    _p("num_grad_quant_bins", 120, int, (),
       "quantization levels per side for use_quantized_grad",
       group="device", check=">0, <=250"),
    _p("speculative_tolerance", 0.0, float, (),
       "relative gain tolerance for preferring already-computed leaf "
       "histograms in the speculative tree builder; 0 = exact "
       "best-first order, small values (e.g. 1e-3) reduce histogram "
       "passes on late flat-gain iterations (device learner only)",
       group="device", check=">=0"),
    _p("wave_splits", False, bool, ("tpu_wave_splits",),
       "apply the top-K splittable leaves per growth step in one batched "
       "histogram pass (K = the speculative pass width) instead of one "
       "leaf at a time: same greedy gain criterion, bulk-synchronous "
       "order — cuts the sequential growth loop from num_leaves-1 steps "
       "to ~log2(K)+num_leaves/K.  Composes with every tree_learner: "
       "serial, data (whole-wave histogram psum), feature (batched "
       "best merge + owner-bit routing psum), voting (batched "
       "elected-only psum)",
       group="device"),
    _p("hist_refinement", True, bool, ("coarse_to_fine",),
       "coarse-to-fine histograms on the wave path: a cheap coarse pass "
       "(bins collapsed 16-to-1) locates the best split region per "
       "(leaf, feature) and one narrow windowed pass resolves it at "
       "fine resolution — ~2x faster histograms at 255 bins.  NOTE: "
       "defaults ON, which makes split SELECTION approximate on "
       "eligible shapes — the chosen split can differ from an "
       "exhaustive scan when the best fine threshold falls outside "
       "the refine window (2 coarse bins around the best coarse "
       "boundary); set false for reference-exact selection.  Quality "
       "is pinned by iteration-matched AUC tests, not split parity. "
       "Missing values are supported (reserved coarse slot + default-"
       "direction scans).  Auto-disabled for categorical features, EFB "
       "bundles, max_bin<48, feature/voting parallel learners, and "
       "shapes where the per-pass fixed cost outweighs the stream "
       "saving (features x padded bins < ~7000)",
       group="device"),
    _p("split_kernel", "auto", str, ("best_split_kernel",),
       "best-split search engine: auto, pallas, xla.  pallas runs the "
       "split scan as a Pallas kernel family fused with the histogram "
       "pass — the batched histogram kernels scan their own "
       "accumulated (leaf, feature-tile) histogram while it is still "
       "VMEM-resident (fused epilogue) and the subtraction-trick "
       "children go through a standalone per-(leaf, feature-tile) "
       "scan kernel with a two-stage tile-then-global argmax — so the "
       "full (leaves x features x bins) histogram is never round-"
       "tripped through HBM between the build and the split search.  "
       "auto = pallas on an accelerator backend, xla elsewhere.  "
       "Numerical features with the serial tree learner only; "
       "categorical features, EFB bundles, forced splits, c2f "
       "refinement (hist_refinement) and parallel learners fall back "
       "to the XLA scans and record the gate in tier telemetry "
       "(superstep records carry split_kernel + split_fallback; "
       "triage_run.py flags an XLA fallback on a TPU backend).  Split "
       "choice is identical to the XLA scan (bit-exact choice, gains "
       "within ~1e-6 relative under monotone clipping); on a CPU "
       "backend split_kernel=pallas runs under the Pallas interpreter "
       "(correctness lane, not a fast path)",
       group="device", check="auto, pallas, xla"),
    _p("fused_iters", 1, int, ("fused_iterations", "superstep_iters"),
       "boosting iterations fused into ONE on-device super-step: a "
       "single jitted lax.scan runs K iterations of gradients + "
       "bagging/GOSS/MVS mask draw + tree build + score update with "
       "the (score, bagging-mask) carry donated, and the K trees' "
       "split records come back in one device->host transfer — "
       "O(iterations/K) Python dispatches and tunnel round-trips "
       "instead of O(iterations).  1 disables (the per-iteration "
       "path).  Bit-exact with the sequential path; parity is pinned "
       "by tests/test_superstep.py.  Distributed tree learners "
       "(tree_learner=data/feature/voting) FUSE: the same K-iteration "
       "scan runs SPMD under shard_map over the learner's mesh with "
       "the strategy collectives inside the one compiled program — K "
       "iterations of sharded build + update cost one dispatch per "
       "block at any mesh size (docs/Distributed.md; sharded parity "
       "pinned by tests/test_sharded_superstep.py).  Automatically "
       "falls back to per-iteration training for: custom objectives "
       "(fobj), objectives with leaf-renewal hooks "
       "(l1/quantile/mape), multi-model-per-iteration objectives "
       "(multiclass), DART/RF boosting, attached validation sets or "
       "training metrics (their eval cadence — including early "
       "stopping — needs per-iteration scores), and the "
       "boost_from_average iteration 0 (which then runs unfused "
       "before fusion engages).  Super-steps are auto-sized down "
       "near the num_iterations boundary (the tail block runs a "
       "shorter scan; expect one extra XLA compile there).  A "
       "learning_rates schedule (reset_parameter callback) changing "
       "the shrinkage mid-block triggers an exact rewind + "
       "redispatch — correct, but it rebuilds the block every "
       "iteration and negates the fusion win; prefer a constant "
       "learning_rate with fused_iters.  Combine with "
       "superstep_pipeline_depth to also hide the one per-block "
       "device->host record fetch behind the next block's dispatch",
       group="device", check=">=1"),
    _p("superstep_pipeline_depth", 1, int, ("pipeline_depth",),
       "fused super-step blocks kept IN FLIGHT beyond the one being "
       "served (fused_iters > 1 only): block K+1 is dispatched BEFORE "
       "block K's stacked split records are fetched, so the one "
       "device->host round-trip per block hides behind the next "
       "block's device compute instead of stalling the loop (the r04 "
       "phase profile showed that fetch at 734.5 ms/iter vs ~4 ms for "
       "everything else).  The healthy-path device-call budget stays "
       "2 per K-block at any depth (pinned by tools/prof_superstep.py"
       "'s pipelined cell) and training remains BIT-exact with depth "
       "0: the in-flight queue drains exactly at the boundaries that "
       "already force one (the no-split stop probe, a mid-block "
       "checkpoint alignment, a learning-rate change, the preempt "
       "flag, a numerical-health trip, elastic rewind/re-mesh), with "
       "each queued block's dispatch fence restoring the host-RNG and "
       "quantization-stream draws it consumed.  0 disables (dispatch "
       "then fetch, the pre-pipelining behavior); engine.train "
       "auto-disables it under a learning_rates schedule (every "
       "pre-dispatched block would be rebuilt).  Per-block telemetry: "
       "fetch_overlap_s / pipeline_depth on superstep records; "
       "triage_run.py flags overlap ~ 0 at depth > 0 as pipelining "
       "silently disabled", group="device", check=">=0"),
    _p("predict_device_handoff", True, bool, ("device_handoff",),
       "serve same-process predict/serve/publish straight from the "
       "training-side packed per-tree tables: each tree's flat "
       "predictor row (ops/predict.py) is extracted ONCE when the "
       "tree materializes from the training fetch, and "
       "flatten_forest_device assembles the engine's SoA tables from "
       "those cached rows — zero full-forest host repacks at the "
       "train->predict seam (counter flatten_full_repacks stays 0 "
       "in-process; flatten_device_handoffs counts the fast path), "
       "byte-identical to the cold-load flatten_forest path (pinned "
       "by tests/test_pipeline.py).  false = always rebuild via "
       "flatten_forest (the model-file/cold-load path)", group="io"),
    # ---- serve (online serving subsystem, lightgbm_tpu/serve/) ----
    _p("serve_host", "127.0.0.1", str, (),
       "bind address of the task=serve HTTP endpoint", group="serve"),
    _p("serve_port", 9595, int, (),
       "port of the task=serve HTTP endpoint (0 = ephemeral)",
       group="serve", check=">=0"),
    _p("serve_max_batch_rows", 1024, int, ("serve_batch_rows",),
       "micro-batcher row cap: concurrent requests coalesce into one "
       "device batch of at most this many rows, and it doubles as the "
       "engine row-chunk for serving — the servable bucket set is the "
       "power-of-two ladder {512, ..., serve_max_batch_rows}, all "
       "pre-warmed at publish so steady-state serving never compiles",
       group="serve", check=">0"),
    _p("serve_batch_wait_ms", 2.0, float, ("serve_max_wait_ms",),
       "micro-batcher max wait: a batch closes when it reaches "
       "serve_max_batch_rows or when the OLDEST admitted request has "
       "waited this long — the latency/throughput knob (0 = dispatch "
       "immediately)", group="serve", check=">=0"),
    _p("serve_queue_rows", 16384, int, (),
       "admission bound in ROWS: total rows pending in the serve "
       "queue; beyond it requests are rejected with a retry-after "
       "hint (HTTP 429) unless they outrank pending work",
       group="serve", check=">0"),
    _p("serve_queue_requests", 1024, int, (),
       "admission bound in REQUESTS (guards against many tiny "
       "requests exhausting queue slots under the row bound)",
       group="serve", check=">0"),
    _p("serve_timeout_ms", 2000.0, float, (),
       "default per-request deadline: expired requests are swept "
       "from the queue without wasting a dispatch (HTTP 504); "
       "0 disables, per-request timeout_ms overrides",
       group="serve", check=">=0"),
    _p("serve_workers", 1, int, (),
       "dispatcher threads draining the micro-batcher (each dispatch "
       "is one engine call; >1 overlaps host-side assembly with "
       "device compute)", group="serve", check=">=1"),
    _p("serve_warmup", True, bool, (),
       "pre-compile every bucket kernel when a model version is "
       "published, BEFORE it becomes the admission target — the "
       "zero-steady-state-compile contract; disable only for "
       "debugging", group="serve"),
    _p("serve_fastpath_max_rows", 8, int, (),
       "single-row fast path: a predict batch with at most this many "
       "rows AND a shallow queue (serve_fastpath_max_queue) skips the "
       "512-row minimum bucket and dispatches on a tiny power-of-two "
       "bucket compiled per fingerprint at publish — the occupancy-"
       "routed p50 lane.  Outputs are bit-identical to the bucketed "
       "engine (pinned by tests/test_shap_engine.py); 0 disables",
       group="serve", check=">=0"),
    _p("serve_fastpath_max_queue", 2, int, (),
       "fast-path occupancy gate: the tiny-bucket lane is taken only "
       "when at most this many requests remain queued behind the "
       "batch — under load the batcher keeps coalescing into the big "
       "warmed buckets instead of serializing many small dispatches",
       group="serve", check=">=0"),
    _p("serve_max_body_bytes", 33554432, int, ("serve_max_body",),
       "HTTP front body-size bound: requests with a larger "
       "Content-Length are rejected with a structured 413 before the "
       "body is read (hardening against oversized/abusive payloads)",
       group="serve", check=">0"),
    _p("serve_drain_grace_s", 10.0, float, ("serve_drain_grace",),
       "graceful-drain window on SIGTERM/SIGINT: the server stops "
       "admitting (503 + Retry-After), finishes already-admitted "
       "requests for up to this long, then exits — so supervisor-"
       "driven restarts never drop admitted requests",
       group="serve", check=">=0"),
    _p("serve_port_file", "", str, (),
       "when set, the HTTP front writes its bound port to this file "
       "once listening — ephemeral-port (serve_port=0) discovery for "
       "the fleet supervisor", group="serve"),
    _p("serve_debug_faults", False, bool, (),
       "expose POST/GET /faults, the remote driving surface of the "
       "fault-injection registry (utils/faults.py) — chaos tests "
       "only, NEVER in production", group="serve"),
    _p("serve_metrics", True, bool, ("serve_metrics_enabled",),
       "expose GET /metrics (Prometheus text format) on the serve "
       "HTTP front: live request counters by status, bounded latency/"
       "occupancy histograms, queue-depth gauges, and every process-"
       "wide telemetry counter mirrored as ltpu_telemetry_* — the "
       "scrape surface FleetSupervisor.metrics_text aggregates "
       "across replicas (docs/Observability.md)", group="serve"),
    _p("serve_metrics_latency_buckets", "", str, (),
       "comma-separated upper bounds (ms) of the serve latency "
       "histogram buckets; '' = the built-in log-spaced ladder "
       "0.5ms..30s.  Bounded histograms are why a long-lived "
       "replica's /stats and /metrics memory is O(1)", group="serve"),
    # ---- route (resilient routing front: serve/router.py) ----
    _p("route_host", "127.0.0.1", str, (),
       "bind address of the task=route HTTP routing front",
       group="route"),
    _p("route_port", 9700, int, (),
       "port of the routing front (0 = ephemeral)", group="route",
       check=">=0"),
    _p("route_port_file", "", str, (),
       "when set, the routing front writes its bound port here once "
       "listening (ephemeral-port discovery, like serve_port_file)",
       group="route"),
    _p("route_probe_interval_s", 0.25, float, (),
       "backend /healthz scrape cadence: the balancer's live view of "
       "health, draining state and per-tenant fingerprints — a "
       "mid-drain or stale-model replica leaves the rotation within "
       "one scrape", group="route", check=">0"),
    _p("route_probe_timeout_s", 2.0, float, (),
       "per-scrape timeout; an unreachable backend leaves the "
       "rotation until a scrape succeeds again", group="route",
       check=">0"),
    _p("route_timeout_ms", 10000.0, float, (),
       "per-request total routing budget: retries, backoff sleeps and "
       "the hedge all fit INSIDE it (a per-request timeout_ms field "
       "tightens it further); exhausted -> structured 504",
       group="route", check=">0"),
    _p("route_max_retries", 2, int, (),
       "routing attempts beyond the first on connect failure / 5xx "
       "(each to a different backend when one exists; the tail-latency "
       "hedge does not count against this bound)", group="route",
       check=">=0"),
    _p("route_backoff_base_ms", 25.0, float, (),
       "retry backoff base: attempt n waits base * 2^(n-1) ms (capped "
       "at route_backoff_max_ms) plus deterministic jitter, clamped "
       "to the request's remaining budget", group="route", check=">=0"),
    _p("route_backoff_max_ms", 1000.0, float, (),
       "retry backoff cap", group="route", check=">=0"),
    _p("route_backoff_jitter", 0.5, float, (),
       "jitter fraction on the retry backoff (deterministic per "
       "request id/attempt, seeded by `seed` — spreads a retry herd "
       "without making tests flaky)", group="route", check=">=0"),
    _p("route_hedge_ms", 75.0, float, (),
       "tail-latency hedging: once the first attempt has been silent "
       "this long, a second attempt goes to a DIFFERENT backend; the "
       "first answer wins and the loser's connection is cancelled "
       "(one hedge per request; 0 disables).  obs/rules.py flags a "
       "hedge rate above 20% as MED — hedges are a tail rescue, not "
       "a steady state", group="route", check=">=0"),
    _p("route_breaker_failures", 3, int, (),
       "per-backend circuit breaker: consecutive forwarding failures "
       "before the backend leaves the balancer's rotation",
       group="route", check=">=1"),
    _p("route_breaker_cooldown_s", 5.0, float, (),
       "after this long an open backend circuit half-opens and "
       "exactly ONE probe request is let through (single-flight); "
       "success closes the circuit, failure re-opens it",
       group="route", check=">=0"),
    _p("route_rows_per_s", 0.0, float, (),
       "per-model admission budget: token-bucket refill rate in "
       "rows/s (0 = unlimited).  An exhausted budget sheds with a "
       "structured 429 + Retry-After BEFORE any backend sees the "
       "request; priority > 0 requests may overdraw one extra burst "
       "before shedding (cheap traffic sheds first).  Override per "
       "model via Router.add_model", group="route", check=">=0"),
    _p("route_burst_rows", 8192, int, (),
       "per-model token-bucket burst capacity in rows", group="route",
       check=">0"),
    _p("route_max_inflight", 256, int, (),
       "per-model in-flight request cap at the router (0 = "
       "unlimited); beyond it low-priority requests shed with 429",
       group="route", check=">=0"),
    _p("route_explain_cost", 4.0, float, (),
       "admission weight of one explain row: POST /v1/<model>/explain "
       "charges the SAME per-model token bucket as predict, "
       "multiplied by this factor (TreeSHAP does O(depth^2) work per "
       "leaf where predict does O(depth)), so explain bursts shed "
       "before they starve the predict lane", group="route",
       check=">=1"),
    _p("route_backends", "", str, (),
       "static backend table for task=route: comma-separated entries "
       "'http://host:port' (default tenant) or "
       "'name=http://a:1+http://b:2' (named tenant over several "
       "replicas).  Programmatic routers attach FleetSupervisors "
       "instead (Router.add_model)", group="route"),
    # ---- fleet (resilience layer: serve/fleet.py, serve/watcher.py) ----
    _p("fleet_replicas", 2, int, ("serve_replicas",),
       "serve processes the fleet supervisor runs; each replica pins "
       "its own engine cache (shared-nothing)", group="fleet",
       check=">=1"),
    _p("fleet_probe_interval_s", 0.5, float, (),
       "supervisor health-probe cadence (/healthz per replica)",
       group="fleet", check=">0"),
    _p("fleet_probe_timeout_s", 2.0, float, (),
       "per-probe timeout; a hung replica (alive process, wedged "
       "front) fails probes and is restarted like a crash",
       group="fleet", check=">0"),
    _p("fleet_fail_threshold", 3, int, (),
       "consecutive failed probes before a live replica is declared "
       "unhealthy and restarted (a dead process restarts immediately)",
       group="fleet", check=">=1"),
    _p("fleet_backoff_base_s", 0.5, float, (),
       "restart backoff base: attempt n waits base * 2^(n-1) seconds "
       "(capped at fleet_backoff_max_s) plus deterministic jitter",
       group="fleet", check=">=0"),
    _p("fleet_backoff_max_s", 30.0, float, (),
       "restart backoff cap", group="fleet", check=">=0"),
    _p("fleet_backoff_jitter", 0.2, float, (),
       "jitter fraction on the restart backoff (deterministic per "
       "slot/attempt, seeded by `seed` — avoids thundering-herd "
       "restarts without making tests flaky)", group="fleet",
       check=">=0"),
    _p("fleet_circuit_failures", 5, int, (),
       "circuit breaker: consecutive failed restart attempts before "
       "the replica slot is removed from rotation (the fleet degrades "
       "gracefully instead of burning CPU on a crash loop)",
       group="fleet", check=">=1"),
    _p("fleet_circuit_cooldown_s", 60.0, float, (),
       "after this long an open circuit half-opens and one restart is "
       "retried; 0 keeps the slot out until operator action",
       group="fleet", check=">=0"),
    _p("watch_poll_s", 2.0, float, ("watch_interval_s",),
       "checkpoint-root watcher poll cadence: new finalized ckpt_* "
       "snapshots are validated (manifest hashes + canary scoring) "
       "and auto-published; corrupt or mis-scoring snapshots are "
       "skipped with a telemetry anomaly", group="fleet", check=">0"),
    _p("watch_tenant", "default", str, (),
       "named tenant the continual watcher (and task=sweep) publishes "
       "models under: replicas load it via the routing front's "
       "/v1/<tenant>/... endpoints while 'default' keeps the unnamed "
       "routes working", group="fleet"),
    _p("canary_file", "", str, (),
       "npz of pinned reference rows the watcher scores every "
       "candidate snapshot on before publishing: array 'X' (rows), "
       "optional 'expected' (predictions pinned within "
       "canary_tolerance) and/or 'label' (quality gate via "
       "canary_min_auc)", group="fleet"),
    _p("canary_min_auc", 0.0, float, (),
       "minimum AUC of canary predictions against the canary 'label' "
       "array; a snapshot scoring below it is NOT published "
       "(0 disables the quality gate)", group="fleet", check=">=0"),
    _p("canary_tolerance", 1e-6, float, (),
       "relative+absolute tolerance for pinned 'expected' canary "
       "predictions", group="fleet", check=">=0"),
    _p("rollback_window_s", 10.0, float, (),
       "post-publish observation window: after it elapses the "
       "rollback controller compares the window's serve telemetry "
       "rollups against the pre-publish window", group="fleet",
       check=">0"),
    _p("rollback_min_requests", 50, int, (),
       "minimum requests inside the observation window before a "
       "verdict is reached (too little traffic extends the window "
       "instead of deciding on noise)", group="fleet", check=">=1"),
    _p("rollback_error_rate", 0.05, float, (),
       "rollback trigger: post-publish bad-request rate (shed/timeout"
       "/error/5xx per request) exceeding the pre-publish rate by "
       "this much republishes the previous version", group="fleet",
       check=">=0"),
    _p("rollback_p99_factor", 3.0, float, (),
       "rollback trigger: post-publish p99 latency above factor x "
       "the pre-publish p99 (and above rollback_p99_floor_ms)",
       group="fleet", check=">0"),
    _p("rollback_p99_floor_ms", 5.0, float, (),
       "p99 regressions below this absolute latency never trigger a "
       "rollback (sub-floor jitter is noise, not a regression)",
       group="fleet", check=">=0"),
    _p("rollback_holddown_s", 60.0, float, (),
       "after a rollback, snapshots with the rolled-back model's "
       "fingerprint are skipped (reason=holddown) for this long — a "
       "regressing deploy cannot flap back in", group="fleet",
       check=">=0"),
    # ---- sweep (many-model battery training: models/battery.py) ----
    _p("sweep_grid", "", str, (),
       "hyperparameter grid for task=sweep as "
       "'param=v1,v2;param2=v3,v4' — the cartesian product defines "
       "the candidate set.  Candidates varying only traced per-model "
       "params (learning_rate, seeds, feature_fraction) share ONE "
       "compiled program (docs/Sweep.md)", group="sweep"),
    _p("sweep_random", 0, int, (),
       "instead of the full cartesian product, sample this many "
       "candidates uniformly from the grid's choices (0 = full grid)",
       group="sweep", check=">=0"),
    _p("sweep_seed", 0, int, (),
       "seed of the random-candidate sampler", group="sweep"),
    _p("sweep_folds", 3, int, ("sweep_nfold",),
       "k-fold CV folds scored per candidate; fold masks ride as "
       "per-model weight vectors over the ONE shared dataset (no "
       "data replication).  1 = no CV (requires sweep_train_full for "
       "winner selection by training metric)", group="sweep",
       check=">=1"),
    _p("sweep_fold_seed", 0, int, (),
       "seed of the CV fold shuffle", group="sweep"),
    _p("sweep_metric", "", str, (),
       "metric scoring each candidate's held-out fold rows per "
       "iteration (l2, rmse, l1, binary_logloss, binary_error, auc); "
       "'' picks the objective's default.  Winner = best mean CV "
       "score at its best iteration", group="sweep"),
    _p("sweep_train_full", True, bool, (),
       "also train every candidate on ALL rows inside the same "
       "compiled battery, so the winner's full-data model exports "
       "without a refit pass", group="sweep"),
    _p("sweep_shard_models", False, bool, (),
       "lay the battery's model axis onto the device mesh when it "
       "tiles evenly (spare devices train disjoint members; no "
       "collectives, bit-identical results)", group="sweep"),
    # ---- continual (long-running trainer daemon, lightgbm_tpu/cont/) ----
    _p("continual_ingest_dir", "", str, ("ingest_dir",),
       "batch source directory of the continual training daemon "
       "(task=continual, docs/Continual.md): npz shards (arrays X and "
       "y/label, optional weight/group) or mmap .X.npy/.y.npy pairs, "
       "consumed in name order.  Each accepted batch runs "
       "ingest -> validate -> extend/refit -> checkpoint; the "
       "checkpoint root doubles as the serve tier's watched publish "
       "root", group="continual"),
    _p("continual_quarantine_dir", "", str, (),
       "where rejected batches are MOVED (schema/drift/non-finite "
       "validation failures, unreadable files, batches that "
       "repeatedly stall or crash the trainer); '' = "
       "<continual_ingest_dir>/_quarantine.  Every move emits a "
       "continual/quarantine telemetry record with the reason",
       group="continual"),
    _p("continual_processed_dir", "", str, (),
       "where consumed batches are moved after their batch-end "
       "checkpoint is durable; '' = <continual_ingest_dir>/_processed",
       group="continual"),
    _p("continual_rounds_per_batch", 10, int, ("rounds_per_batch",),
       "boosting iterations the daemon trains per accepted batch in "
       "extend mode (warm-start continue-training from the current "
       "model)", group="continual", check=">=1"),
    _p("continual_refit_every", 0, int, (),
       "every Nth accepted batch is consumed as a REFIT (leaf-value "
       "recalibration on the fresh batch, decay refit_decay_rate) "
       "instead of growing trees; the refit snapshot re-saves the "
       "current boundary and the watcher republishes it on the "
       "fingerprint change.  0 = always extend", group="continual",
       check=">=0"),
    _p("continual_poll_s", 1.0, float, (),
       "ingest-directory poll cadence when no batch is pending",
       group="continual", check=">0"),
    _p("continual_idle_exit_s", 0.0, float, (),
       "exit the daemon after this long with no new batches (CI/"
       "drain-and-stop mode); 0 = run until preempted",
       group="continual", check=">=0"),
    _p("continual_max_batches", 0, int, (),
       "stop after consuming this many batches (tests/benchmarks); "
       "0 = unbounded", group="continual", check=">=0"),
    _p("continual_stall_timeout_s", 120.0, float, (),
       "watchdog: a train step that goes this long without a "
       "heartbeat (one per boosting iteration) is declared stalled — "
       "the attempt is abandoned and the batch retries from the last "
       "snapshot (continual/stall_restart telemetry).  0 disables",
       group="continual", check=">=0"),
    _p("continual_max_batch_retries", 2, int, (),
       "stall/crash retries per batch before it is quarantined "
       "(reason stall|error) and its in-flight checkpoints pruned",
       group="continual", check=">=0"),
    _p("continual_read_retries", 3, int, (),
       "bounded retries for TRANSIENT batch-read failures (OSError) "
       "before the file is quarantined (reason read)",
       group="continual", check=">=0"),
    _p("continual_backoff_base_s", 0.1, float, (),
       "exponential-backoff base between ingest read retries "
       "(attempt n sleeps base * 2^(n-1), capped)", group="continual",
       check=">=0"),
    _p("continual_backoff_max_s", 5.0, float, (),
       "ingest read-retry backoff cap", group="continual", check=">=0"),
    _p("continual_drift_sigma", 8.0, float, (),
       "label-distribution drift gate: a batch whose label mean is "
       "more than this many reference standard deviations from the "
       "running reference (accepted batches so far) is quarantined; "
       "0 disables", group="continual", check=">=0"),
    _p("continual_range_factor", 10.0, float, (),
       "feature-range drift gate: batch values outside the reference "
       "min/max inflated by this factor of the per-feature span are "
       "quarantined; 0 disables", group="continual", check=">=0"),
    _p("continual_nonfinite_check", True, bool, (),
       "ingest-side non-finite scan (NaN/inf in X or labels fails "
       "validation).  Disabling it leaves the in-training numerical-"
       "health guard (utils/health.py) as the only defense — the "
       "guard rewinds exactly and quarantines the batch, but only "
       "after paying for the doomed dispatch", group="continual"),
    _p("continual_snapshot_freq", 0, int, (),
       "in-batch periodic checkpoint cadence (iterations) while the "
       "daemon trains a batch; 0 = checkpoint only at batch "
       "boundaries (the default keeps the exact quarantine rewind "
       "within keep_last_n retention)", group="continual", check=">=0"),
    # ---- obs (observability plane: lightgbm_tpu/obs/) ----
    _p("obs_flight_recorder", False, bool, ("flight_recorder",),
       "arm the anomaly-triggered flight recorder (obs/flight.py): a "
       "bounded in-memory ring of recent telemetry records plus the "
       "online anomaly rules (retrace storm, pipelining-disabled, "
       "XLA-fallback-on-TPU, stall, rollback, nonfinite — shared "
       "with triage_run.py); a firing rule dumps the ring and, on "
       "device backends, a time-boxed jax.profiler trace into "
       "obs_capture_dir with a 'capture' telemetry record pointing "
       "at it", group="obs"),
    _p("obs_capture_dir", "", str, (),
       "flight-recorder capture root; '' = obs_captures/ next to "
       "telemetry_file (or the working directory)", group="obs"),
    _p("obs_ring_records", 2048, int, (),
       "flight-recorder ring capacity: how many recent telemetry "
       "records a capture dumps", group="obs", check=">0"),
    _p("obs_capture_profile_ms", 2000, int, (),
       "length of the time-boxed jax.profiler trace a capture "
       "records on a live device backend (0 skips profiling; the "
       "trace stops on a daemon thread so the hot path never "
       "blocks)", group="obs", check=">=0"),
    _p("obs_capture_cooldown_s", 60.0, float, (),
       "debounce between flight-recorder captures — an anomaly "
       "storm costs a handful of dumps, not a disk", group="obs",
       check=">=0"),
    _p("obs_max_captures", 4, int, (),
       "capture budget per process; further anomalies only log",
       group="obs", check=">=1"),
    # ---- slo (SLO engine: lightgbm_tpu/obs/slo.py) ----
    _p("slo_enable", False, bool, (),
       "run the SLO engine next to the routing front (task=route): "
       "declarative objectives (availability, latency-vs-target, "
       "queue saturation, per-model shed rate) evaluated with multi-"
       "window multi-burn-rate alerting; every tick emits slo "
       "telemetry records, sets ltpu_slo_* gauges, and feeds the "
       "shared anomaly rules (obs/rules.py)", group="slo"),
    _p("slo_interval_s", 5.0, float, (),
       "SLO evaluation cadence (one tick scrapes every objective "
       "source and re-judges every window)", group="slo", check=">0"),
    _p("slo_window_fast_s", 60.0, float, (),
       "fast burn window: the page-grade alert fires only when the "
       "burn exceeds slo_fast_burn on BOTH this and the mid window "
       "(fast to fire, hard to blip)", group="slo", check=">0"),
    _p("slo_window_mid_s", 300.0, float, (),
       "mid burn window confirming the fast alert", group="slo",
       check=">0"),
    _p("slo_window_slow_s", 1800.0, float, (),
       "slow burn window: the ticket-grade alert fires on this "
       "window alone at slo_slow_burn", group="slo", check=">0"),
    _p("slo_fast_burn", 14.4, float, (),
       "page-grade burn-rate threshold (multiples of 'exactly on "
       "target' budget spend; 14.4 spends a 30-day budget in ~2 "
       "days)", group="slo", check=">0"),
    _p("slo_slow_burn", 3.0, float, (),
       "ticket-grade burn-rate threshold on the slow window alone",
       group="slo", check=">0"),
    _p("slo_budget_window_s", 86400.0, float, (),
       "wall-clock error-budget accounting period; budget consumed "
       "and remaining are tracked over this window and persisted "
       "across restarts via slo_state_file", group="slo", check=">0"),
    _p("slo_state_file", "", str, (),
       "error-budget persistence path (atomic tmp+rename each tick); "
       "a restarting serve tier re-adopts its burned budget instead "
       "of laundering it.  '' = in-memory only", group="slo"),
    _p("slo_availability_target", 0.999, float, (),
       "availability objective: fraction of terminal responses that "
       "must be ok (non-error, non-shed)", group="slo"),
    _p("slo_latency_p99_ms", 250.0, float, (),
       "latency objective: the rolling p99 each tick must be at or "
       "under this many milliseconds to count as a good sample",
       group="slo", check=">0"),
    _p("slo_latency_target", 0.99, float, (),
       "latency objective target: fraction of ticks whose rolling "
       "p99 met slo_latency_p99_ms", group="slo"),
    _p("slo_queue_saturation", 0.8, float, (),
       "queue objective: in-flight occupancy (in-flight requests / "
       "total max_inflight capacity) at or above this fraction makes "
       "the tick a bad sample", group="slo"),
    _p("slo_queue_target", 0.99, float, (),
       "queue objective target: fraction of ticks below "
       "slo_queue_saturation occupancy", group="slo"),
    _p("slo_shed_target", 0.99, float, (),
       "per-model shed objective target: fraction of requests NOT "
       "turned away by the admission budgets (one objective per "
       "registered model, named shed:<model>)", group="slo"),
    # ---- autoscale (closed-loop controller: serve/autoscaler.py) ----
    _p("autoscale", False, bool, ("autoscale_enable",),
       "run the closed-loop autoscaler next to the routing front "
       "(task=route with a fleet): consumes the SLO burn rates + "
       "live router gauges and grows/drains FleetSupervisor replicas "
       "and retunes per-model admission budgets; every decision is a "
       "traced autoscale telemetry record with its evidence inline",
       group="autoscale"),
    _p("autoscale_dry_run", False, bool, (),
       "compute and emit identical decisions (mode=dry_run) without "
       "touching the fleet or the buckets — the rehearsal mode for "
       "tuning thresholds against live traffic", group="autoscale"),
    _p("autoscale_interval_s", 2.0, float, (),
       "control-loop cadence", group="autoscale", check=">0"),
    _p("autoscale_min_replicas", 1, int, (),
       "the controller never drains below this replica count",
       group="autoscale", check=">=1"),
    _p("autoscale_max_replicas", 4, int, (),
       "the controller never grows above this replica count; at max "
       "it falls back to the admission lever (shed cheap traffic "
       "first)", group="autoscale", check=">=1"),
    _p("autoscale_grow_burn", 2.0, float, (),
       "grow trigger: SLO fast burn above this on BOTH fast windows "
       "(page-grade evidence, not a blip)", group="autoscale",
       check=">0"),
    _p("autoscale_grow_queue", 0.8, float, (),
       "grow trigger: in-flight occupancy at/above this fraction of "
       "total routing capacity", group="autoscale", check=">0"),
    _p("autoscale_drain_idle_s", 60.0, float, (),
       "drain hysteresis: quiet (low occupancy AND no burn) must be "
       "sustained this long before one replica drains",
       group="autoscale", check=">=0"),
    _p("autoscale_drain_util", 0.2, float, (),
       "quiet means in-flight occupancy below this fraction (must be "
       "< autoscale_grow_queue — the gap is the anti-flap deadband)",
       group="autoscale", check=">=0"),
    _p("autoscale_cooldown_s", 30.0, float, (),
       "minimum spacing between grow actions", group="autoscale",
       check=">=0"),
    _p("autoscale_drain_cooldown_s", 60.0, float, (),
       "minimum spacing between drain actions (slower than grow: "
       "adding capacity is cheap, removing it under load is not)",
       group="autoscale", check=">=0"),
    _p("autoscale_shed_rows_per_s", 256.0, float, (),
       "per-model token-bucket rate while a shed retune is active "
       "(priority > 0 requests keep their overdraw reserve, so cheap "
       "traffic sheds first); originals are restored once the burn "
       "clears", group="autoscale", check=">0"),
    _p("autoscale_budget_floor", 0.25, float, (),
       "retune admission down once SLO budget remaining falls below "
       "this fraction even without an active burn — spend the last "
       "quarter of the budget slowly", group="autoscale", check=">=0"),
]

_PARAM_BY_NAME: Dict[str, Param] = {p.name: p for p in PARAMS}

# alias -> canonical name (aliases AND canonical names both resolve)
ALIAS_TABLE: Dict[str, str] = {}
for _param in PARAMS:
    ALIAS_TABLE[_param.name] = _param.name
    for _a in _param.aliases:
        ALIAS_TABLE[_a] = _param.name


def param_docs() -> str:
    """Render parameter docs (the reference generates Parameters.rst)."""
    lines = []
    group = None
    for p in PARAMS:
        if p.group != group:
            group = p.group
            lines.append(f"\n## {group}\n")
        alias = f" (aliases: {', '.join(p.aliases)})" if p.aliases else ""
        lines.append(f"- `{p.name}` = `{p.default!r}`{alias}: {p.desc}")
    return "\n".join(lines)


_TRUE = {"true", "1", "yes", "on", "+", "t", "y"}
_FALSE = {"false", "0", "no", "off", "-", "f", "n"}


def _coerce(param: Param, value: Any) -> Any:
    if value is None:
        return None
    if param.type is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, (int, float)):
            return bool(value)
        s = str(value).strip().lower()
        if s in _TRUE:
            return True
        if s in _FALSE:
            return False
        raise ValueError(f"cannot parse bool parameter {param.name}={value!r}")
    if param.type is int:
        return int(float(value))
    if param.type is float:
        return float(value)
    if param.type is list:
        if isinstance(value, (list, tuple)):
            return list(value)
        if isinstance(value, str):
            if not value.strip():
                return []
            return [_num(tok) for tok in value.replace(";", ",").split(",")]
        return [value]
    if param.type is str:
        return str(value)
    return value


def _num(tok: str) -> Any:
    tok = tok.strip()
    try:
        f = float(tok)
        return int(f) if f == int(f) and "." not in tok and "e" not in tok.lower() else f
    except ValueError:
        return tok


class Config:
    """Resolved configuration.

    ``Config(params)`` resolves aliases (later aliases never override an
    explicitly-set canonical name, mirroring ``Config::KV2Map``), coerces
    types, applies the master ``seed`` to the specific seeds
    (``config.cpp GetAliasAndSeed`` behavior) and keeps unknown keys in
    ``raw`` for forward-compat.
    """

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        for p in PARAMS:
            object.__setattr__(self, p.name,
                               list(p.default) if isinstance(p.default, list)
                               else p.default)
        self.raw: Dict[str, Any] = {}
        self._user_set: set = set()
        if params:
            self.update(params)

    def update(self, params: Dict[str, Any]) -> None:
        resolved: Dict[str, Any] = {}
        explicit: set = set()
        for key, value in params.items():
            canon = ALIAS_TABLE.get(key)
            if canon is None:
                self.raw[key] = value
                continue
            if canon in resolved and (canon in explicit or key != canon):
                # canonical name wins over aliases; first alias wins otherwise
                if key == canon:
                    resolved[canon] = value
                    explicit.add(canon)
                else:
                    Log.warning("%s is set with %s=%r, %s=%r will be ignored. "
                                "Current value: %s=%r", canon, canon,
                                resolved[canon], key, value, canon,
                                resolved[canon])
                continue
            resolved[canon] = value
            if key == canon:
                explicit.add(canon)
        for canon, value in resolved.items():
            try:
                setattr(self, canon, _coerce(_PARAM_BY_NAME[canon], value))
            except (TypeError, ValueError) as e:
                Log.fatal("bad value for parameter %s: %s", canon, e)
            self._user_set.add(canon)
        # master seed fans out to seeds never explicitly set by the user
        # (in this or any earlier update)
        if self.seed is not None:
            seed = int(self.seed)
            for name, offset in (("bagging_seed", 3),
                                 ("feature_fraction_seed", 2),
                                 ("drop_seed", 4), ("data_random_seed", 1)):
                if name not in self._user_set:
                    setattr(self, name, seed + offset)
        self._validate()
        self._warn_inert()
        # only an explicit user setting moves the global log level — a
        # default-constructed Config (e.g. a valid set with no params)
        # must not clobber the level the training config established
        if "verbosity" in self._user_set:
            Log.reset_level(self.verbosity)

    # params accepted for reference-config compatibility but without
    # effect in the TPU-native design (dense device bins, XLA
    # collectives instead of sockets, one process per host)
    _INERT = {
        "two_round": "data loads in one pass on this backend",
        "is_enable_sparse": "bins are dense device arrays",
        "sparse_threshold": "bins are dense device arrays",
        "gpu_platform_id": "device selection is JAX_PLATFORMS",
        "gpu_device_id": "device selection is JAX_PLATFORMS",
        "gpu_use_dp": "histograms always accumulate in f32 hi/lo pairs",
    }

    def _warn_inert(self) -> None:
        for name in sorted(self._user_set & set(self._INERT)):
            default = next(p.default for p in PARAMS if p.name == name)
            if getattr(self, name) != default:
                Log.warning("parameter %s has no effect: %s", name,
                            self._INERT[name])

    def _validate(self) -> None:
        if self.num_leaves < 2:
            Log.fatal("num_leaves must be >= 2, got %d", self.num_leaves)
        if not (0.0 < self.bagging_fraction <= 1.0):
            Log.fatal("bagging_fraction must be in (0, 1], got %g",
                      self.bagging_fraction)
        if not (0.0 < self.feature_fraction <= 1.0):
            Log.fatal("feature_fraction must be in (0, 1], got %g",
                      self.feature_fraction)
        if self.max_bin <= 1:
            Log.fatal("max_bin must be > 1, got %d", self.max_bin)
        if self.boosting == "goss" and self.top_rate + self.other_rate > 1.0:
            Log.fatal("goss: top_rate + other_rate must be <= 1")
        if self.boosting == "rf" and not (self.bagging_freq > 0 and
                                          0 < self.bagging_fraction < 1):
            Log.fatal("random forest requires bagging "
                      "(bagging_freq > 0, 0 < bagging_fraction < 1)")

    def to_dict(self) -> Dict[str, Any]:
        d = {p.name: getattr(self, p.name) for p in PARAMS}
        d.update(self.raw)
        return d

    def copy(self) -> "Config":
        c = Config()
        for p in PARAMS:
            v = getattr(self, p.name)
            setattr(c, p.name, list(v) if isinstance(v, list) else v)
        c.raw = dict(self.raw)
        c._user_set = set(self._user_set)
        return c

    @staticmethod
    def str2dict(text: str) -> Dict[str, Any]:
        """Parse ``key=value`` parameters (``Config::KV2Map``).

        Accepts both the conf-file form (one pair per line, spaces
        allowed around ``=``) and the C-API/CLI string form
        (space-separated ``k1=v1 k2=v2`` pairs on one line)."""
        out: Dict[str, Any] = {}
        for line in text.splitlines():
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            tokens = line.split()
            if len(tokens) > 1 and all("=" in t for t in tokens):
                for t in tokens:
                    k, v = t.split("=", 1)
                    out[k.strip()] = v.strip()
            else:
                k, v = line.split("=", 1)
                out[k.strip()] = v.strip()
        return out
