"""Elastic recovery for mesh-sharded training: shard-loss detection,
re-mesh over the survivors, and bit-exact continuation.

PR 7 put the distributed learners inside ONE compiled ``shard_map``
super-step — which made the compiled program a single point of
failure: on preemptible slices a lost or hung shard stalls the
collective rendezvous and takes the whole training job with it, and
shard loss is the NORMAL failure mode there, not an edge case.  This
module is the mesh path's failure story, completing the set PR 5
(checkpoint), PR 6 (serving fleet) and PR 8 (continual daemon) gave
the other subsystems:

- **detection** — a per-block heartbeat rides the fused super-step's
  existing host-side block bookkeeping (the same place the
  ``superstep`` telemetry record is assembled), so it costs ZERO extra
  device calls; a collective-stall watchdog (the PR 8 heartbeat
  pattern generalized to the mesh path) runs each fused dispatch on a
  worker thread and abandons it when the heartbeat goes silent past
  ``elastic_stall_timeout_s`` (a hung collective never returns — on a
  real slice that is what losing a peer looks like).  Dispatch
  EXCEPTIONS are classified: collective/device-loss signatures (and
  the ``mesh.collective`` injection point) mean a shard died mid-
  block; anything else — ``NumericalHealthError``, a checkpoint
  fault, a plain bug — propagates untouched.
- **rewind** — nothing from a failed block was served or applied to
  the model: the dispatch fence (``GBDT._dispatch_fence``) restores
  the pre-block host-RNG/quantization-stream state the aborted
  dispatch consumed, and the PR 3 served-boundary replay discards any
  partially-served block, exactly as the checkpoint capture does.
  Under async pipelining (``superstep_pipeline_depth`` > 0) MORE
  THAN ONE block can be outstanding — the live fence always points
  at the OLDEST unfetched dispatch, so one abort restores the draws
  every in-flight block consumed and the whole queue dies with it
  (an abandoned zombie dies on its captured generation token before
  it can append a queue entry or commit a fetched block).
- **re-mesh** — :meth:`GBDT.remesh` rebuilds the mesh over the
  surviving device set, re-places every mesh-resident tensor under
  the new ``DistributedBuilder.shardings()`` and rebuilds the fused
  scan (the superstep program is keyed by mesh shape), continuing
  from the served boundary.
- **parity contract** — the recovered model is BIT-IDENTICAL to an
  uninterrupted run over the surviving mesh from the rewind boundary:
  gradients, mask draws and the score update are replicated (the PR 7
  bit-exactness anchor), the host PRNG streams are rewound exactly,
  and the score carry is replayed to the boundary.  Cross-width
  caveat: the data/voting learners' float histogram ``psum`` groups
  rows per shard, so tree prefixes TRAINED at different widths differ
  in float low bits — the oracle for byte-equality therefore shares
  the prefix (a clean continuation at the surviving width), while
  feature-parallel — which reduces no float histograms — is byte-
  identical to serial at EVERY width, prefix included
  (``docs/Distributed.md``).

Surviving-set policy: when the failure names a device (real runtimes
usually do; the classifier keeps the message) the mesh is rebuilt
without it; otherwise the HIGHEST-index device is dropped — a
deterministic stand-in that keeps the chaos harness and the parity
oracles reproducible.  A 2-D (data x feature) mesh cannot drop a
single device — shardings need a full rectangle — so recovery drops
the whole mesh ROW or COLUMN that loses fewer devices
(:func:`degrade_mesh_shape`: a lost device on a 4x2 mesh re-meshes to
3x2, sacrificing one healthy peer; on a 2x4 to 2x3) and rebuilds the
2-D shardings over the surviving rectangle.  Repeated failures degrade
further, bounded by
``elastic_max_remesh`` and ``elastic_min_shards``; past either bound
the supervisor raises :class:`ElasticError` (fail loudly: the PR 5
checkpoint story owns process-level restart, including resuming an
8-shard snapshot on a narrower host — ``ckpt/manager.py`` re-shards
from the manifest's recorded mesh topology).

Fault-injection points (``utils/faults.py``): ``mesh.collective``
(``error`` | ``hang`` | ``sleep_<ms>``, fired once per fused-block
dispatch), ``mesh.heartbeat`` (``suppress``), ``elastic.remesh``
(``error``).  Chaos harness: ``tools/chaos_elastic.py`` (CI).
"""
from __future__ import annotations

import re
import threading
import time
from typing import Any, Callable, Dict, Optional

from ..utils import faults as _faults
from ..utils import telemetry as _telemetry
from ..utils.log import Log

__all__ = ["ElasticError", "ElasticAbandoned", "ElasticSupervisor",
           "classify_shard_failure", "degrade_mesh_shape"]


def degrade_mesh_shape(r: int, f: int) -> tuple:
    """The 2-D re-mesh policy: on shard loss, drop the full mesh row
    or column that loses FEWER devices (a rectangle is the smallest
    unit a 2-D sharding can shrink by).  Dropping a data-axis row
    loses ``f`` devices; a feature-axis column loses ``r``.  Ties
    prefer the row drop (rows usually dominate the device count, so
    the feature axis — and its O(1/F_axis) histogram-byte cut — is
    preserved longest)."""
    if r > 1 and (f <= r or f == 1):
        return (r - 1, f)
    return (r, f - 1)

# message signatures of a shard/collective failure, matched against
# real XLA/PJRT device-loss errors and the injected stand-in.  Kept
# deliberately narrow: an unrecognized exception must PROPAGATE (a
# NumericalHealthError rewound-and-remeshed would hide bad data).
_SHARD_FAILURE_RE = re.compile(
    r"(?i)(injected collective|collective.+(?:fail|abort|timeout|"
    r"stall)|all[-_ ]?(?:gather|reduce).+(?:fail|abort|timeout)|"
    r"rendezvous|DEADLINE_EXCEEDED|device.+(?:lost|failed|halted|"
    r"unhealthy|removed)|slice.+(?:lost|unhealthy)|"
    r"peer.+(?:down|unreachable)|NCCL|preempt.+(?:worker|host))")


class ElasticError(RuntimeError):
    """Shard-loss recovery exhausted (re-mesh budget or minimum mesh
    width) — the job must fail loudly and restart from checkpoint."""


class ElasticAbandoned(BaseException):
    """Raised INSIDE an abandoned dispatch attempt when its supervisor
    has already moved on (stall watchdog fired): the zombie thread
    must not commit any state.  BaseException so cleanup code guarded
    by ``except Exception`` cannot swallow it."""


def classify_shard_failure(exc: BaseException) -> Optional[str]:
    """Shard-failure detail string when ``exc`` looks like a lost or
    hung shard (collective abort, device loss, the ``mesh.collective``
    injection), else None — the caller re-raises unclassified
    failures untouched."""
    if isinstance(exc, ElasticAbandoned):
        return None
    msg = f"{type(exc).__name__}: {exc}"
    if isinstance(exc, _faults.InjectedFault) and \
            "mesh.collective" in str(exc):
        return msg
    if _SHARD_FAILURE_RE.search(msg):
        return msg
    return None


class _Heartbeat:
    """Monotonic last-sign-of-life timestamp beaten from the fused
    block's host-side bookkeeping (GIL-atomic float — same shape as
    the continual daemon's)."""

    def __init__(self):
        self.t = time.monotonic()
        self.blocks = 0

    def beat(self, block: bool = False) -> None:
        self.t = time.monotonic()
        if block:
            self.blocks += 1

    def age(self) -> float:
        return time.monotonic() - self.t


class ElasticSupervisor:
    """Supervise a sharded booster's update loop: run each fused
    dispatch on a worker thread under the stall watchdog, classify
    failures, and recover by rewind + re-mesh.

    Pure-host serve iterations (``GBDT.next_update_is_local``) run
    inline — supervision adds no device calls and no thread hops to
    them, so the healthy-path budget stays 2 device calls per K-block
    (pinned by ``tools/prof_superstep.py``).
    """

    #: stall-timeout multiple while a mesh identity's first block is
    #: still compiling (same rationale as the continual watchdog's
    #: first-iteration grace)
    COMPILE_GRACE = 5.0

    def __init__(self, booster, stall_timeout_s: Optional[float] = None,
                 max_remesh: Optional[int] = None,
                 min_shards: Optional[int] = None, recorder=None):
        self.booster = booster
        cfg = booster._gbdt.config
        self.stall_timeout_s = float(
            cfg.elastic_stall_timeout_s if stall_timeout_s is None
            else stall_timeout_s)
        self.max_remesh = int(cfg.elastic_max_remesh if max_remesh is None
                              else max_remesh)
        self.min_shards = max(int(cfg.elastic_min_shards
                                  if min_shards is None else min_shards),
                              1)
        self.recorder = recorder
        self.remeshes = 0
        self._gen_lock = threading.Lock()
        self._generation = 0
        self._warm_meshes: set = set()

    # one event -> counter-key map shared with RunRecorder._aggregate
    # (telemetry.py) so counters_snapshot readers and run_end
    # summaries agree on names
    COUNTER_KEYS = {
        "detect": "recovery_detects",
        "remesh": "recovery_remeshes",
        "remesh_failed": "recovery_remesh_failures",
        "reshard": "recovery_reshards",
        "escalate": "recovery_escalations",
    }

    # ------------------------------------------------------------------
    def _emit(self, event: str, **fields) -> None:
        _telemetry.counters.incr(
            self.COUNTER_KEYS.get(event, f"recovery_{event}s"))
        rec = self.recorder or \
            getattr(self.booster._gbdt, "_telemetry", None) or \
            _telemetry.get_recorder()
        if rec is not None:
            rec.emit("recovery", event=event, **fields)

    def _mesh_key(self):
        g = self.booster._gbdt
        if g._dist is None:
            return ("serial", 1, (1,))
        # the mesh SHAPE is part of the identity: a 4x2 and a 2x4
        # data2d mesh compile different programs, so each earns its
        # own first-block compile grace
        return (g._dist.kind, int(g._dist.num_shards),
                tuple(int(s) for s in g._dist.mesh.devices.shape))

    # ------------------------------------------------------------------
    def update(self, fobj=None) -> bool:
        """One supervised boosting iteration (the engine loop's
        ``booster.update`` under elastic training)."""
        g = self.booster._gbdt
        if fobj is not None or g._dist is None:
            # custom gradients / serial fallback: nothing to supervise
            return self.booster.update(fobj=fobj)
        if g.next_update_is_local():
            # serving an already-materialized tree: pure host work
            return self.booster.update()
        while True:
            done, result = self._attempt()
            if done:
                return result

    def _attempt(self):
        """One watched dispatch attempt.  Returns ``(True, stop)`` on
        success; on a classified shard failure recovers (re-mesh) and
        returns ``(False, None)`` so the caller retries the iteration
        on the new mesh.  Unclassified failures propagate."""
        g = self.booster._gbdt
        with self._gen_lock:
            self._generation += 1
            gen = self._generation

        def alive(expect=gen):
            with self._gen_lock:
                return self._generation == expect

        hb = _Heartbeat()
        g._elastic_heartbeat = hb
        g._elastic_alive = alive
        box: Dict[str, Any] = {}

        def run():
            try:
                box["stop"] = self.booster.update()
            except BaseException as exc:  # noqa: BLE001 - classified below
                box["error"] = exc

        th = threading.Thread(target=run, name="ltpu-elastic-dispatch",
                              daemon=True)
        mesh_key = self._mesh_key()
        limit = self.stall_timeout_s
        if limit > 0 and mesh_key not in self._warm_meshes:
            limit *= self.COMPILE_GRACE   # first block compiles here
        th.start()
        stalled = False
        while th.is_alive():
            th.join(0.05)
            if limit > 0 and hb.age() > limit:
                stalled = True
                break
        if stalled:
            with self._gen_lock:
                self._generation += 1   # zombie sees !alive(): it must
            cause, detail = "hang", (   # not commit any state
                f"no heartbeat for {hb.age():.1f}s inside a fused "
                f"block dispatch (collective stall)")
        else:
            err = box.get("error")
            if err is None:
                self._warm_meshes.add(mesh_key)
                return True, box.get("stop", False)
            if isinstance(err, ElasticAbandoned):  # pragma: no cover
                return False, None      # raced a concurrent abandon
            detail = classify_shard_failure(err)
            if detail is None:
                raise err               # not a shard failure
            cause = "error"
        self._recover(cause, detail)
        return False, None

    # ------------------------------------------------------------------
    def _recover(self, cause: str, detail: str) -> None:
        """Rewind to the served boundary and re-mesh over the
        survivors; bounded by ``elastic_max_remesh`` /
        ``elastic_min_shards``, past which :class:`ElasticError`
        escalates to the process-level (checkpoint) recovery story."""
        g = self.booster._gbdt
        # land on a consistent host state FIRST — before any
        # escalation can raise: the dead block's fence (RNG +
        # quantization-stream draws) must be restored even when no
        # re-mesh follows, or a checkpoint taken from the live
        # booster after ElasticError resumes with a drifted RNG
        g.abort_inflight_dispatch()
        width = int(g._dist.num_shards) if g._dist is not None else 1
        boundary = int(g.completed_iterations())
        self._emit("detect", cause=cause, detail=str(detail)[:300],
                   iter=boundary, num_shards=width)
        Log.warning("elastic: shard failure detected (%s) at iteration "
                    "%d on the %d-shard mesh: %s", cause, boundary,
                    width, str(detail)[:200])
        self.remeshes += 1
        if self.remeshes > self.max_remesh:
            self._emit("escalate", reason="max_remesh",
                       num_shards=width, iter=boundary)
            raise ElasticError(
                f"shard-loss recovery exhausted: {self.remeshes - 1} "
                f"re-mesh(es) already spent (elastic_max_remesh="
                f"{self.max_remesh}) — restart from checkpoint "
                f"({cause}: {str(detail)[:200]})")
        # capture the served-boundary snapshot ONCE, before the first
        # remesh attempt can mutate the booster: a remesh that fails
        # AFTER its internal re-construction leaves a blank booster,
        # and a retry snapshotting THAT would silently restart
        # training from iteration 0
        g._fused_rewind()
        g._flush_pending()
        snapshot = g.training_snapshot()
        # 2-D meshes degrade by whole rows/columns so the survivors
        # still tile a rectangle; 1-D meshes shed one shard at a time
        shape = None
        if g._dist is not None and g._dist.kind == "data2d":
            shape = (int(g._dist.row_shards), int(g._dist.feat_shards))
        from_shape = list(shape) if shape is not None else None
        if shape is not None:
            shape = degrade_mesh_shape(*shape)
            survivors = shape[0] * shape[1]
        else:
            survivors = width - 1
        while True:
            if survivors < self.min_shards:
                self._emit("escalate", reason="min_shards",
                           num_shards=width, iter=boundary)
                raise ElasticError(
                    f"only {survivors} shard(s) would survive, below "
                    f"elastic_min_shards={self.min_shards} — restart "
                    f"from checkpoint ({cause}: {str(detail)[:200]})")
            t0 = time.perf_counter()
            use_2d = shape is not None and survivors > 1
            try:
                mode = _faults.fire("elastic.remesh")
                if mode == "error":
                    raise RuntimeError("injected fault "
                                       "(elastic.remesh:error)")
                if use_2d:
                    new_width = g.remesh(mesh_shape=shape,
                                         snapshot=snapshot)
                else:
                    new_width = g.remesh(num_shards=survivors,
                                         snapshot=snapshot)
            except (Exception, _faults.InjectedFault) as exc:
                self._emit("remesh_failed", to_shards=survivors,
                           to_shape=list(shape) if use_2d else None,
                           error=str(exc)[:300])
                Log.warning("elastic: re-mesh to %d shard(s) failed "
                            "(%s); degrading further", survivors, exc)
                if use_2d:
                    shape = degrade_mesh_shape(*shape)
                    survivors = shape[0] * shape[1]
                else:
                    survivors -= 1
                continue
            self._emit("remesh", from_shards=width,
                       to_shards=int(new_width), iter=boundary,
                       cause=cause, from_shape=from_shape,
                       to_shape=list(shape) if use_2d else None,
                       duration_ms=round(
                           (time.perf_counter() - t0) * 1e3, 3))
            Log.warning("elastic: re-meshed %d -> %d shard(s) at "
                        "iteration %d; continuing bit-exactly from "
                        "the served boundary", width, new_width,
                        boundary)
            return
