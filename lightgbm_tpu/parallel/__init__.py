"""Distributed training over a :class:`jax.sharding.Mesh`.

The reference's network layer (``src/network/``, ``include/LightGBM/
network.h:86-257``) hand-builds Bruck / recursive-halving collectives
over TCP/MPI linkers; its three parallel tree learners call
``ReduceScatter`` / ``Allgather`` / allreduce-arg-max on top
(``data_parallel_tree_learner.cpp``, ``feature_parallel_tree_learner
.cpp``, ``voting_parallel_tree_learner.cpp``).  On TPU the whole linker
layer disappears: the mesh, topology and schedules belong to XLA, and
the collectives become ``jax.lax.psum_scatter`` / ``all_gather`` /
``psum`` over a named mesh axis riding ICI (and DCN across slices, via
standard ``jax.distributed`` multi-host init).  What this package keeps
from the reference is the *interface shape* — which learner shards what,
and which reductions run where — as documented on
:class:`~lightgbm_tpu.ops.grow.DistConfig`.
"""
from .elastic import ElasticError, ElasticSupervisor
from .learners import (AXIS_NAME, DATA_AXIS, FEAT_AXIS,
                       DistributedBuilder, factor_mesh_shape,
                       make_mesh_2d, make_mesh_for, parse_mesh_shape,
                       resolve_num_shards)

__all__ = ["AXIS_NAME", "DATA_AXIS", "FEAT_AXIS", "DistributedBuilder",
           "ElasticError", "ElasticSupervisor", "factor_mesh_shape",
           "make_mesh_2d", "make_mesh_for", "parse_mesh_shape",
           "resolve_num_shards"]
