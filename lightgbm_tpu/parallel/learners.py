"""Parallel tree-learner builders: ``shard_map`` wrappers around the
device growth loop.

Maps ``tree_learner={data,feature,voting}`` (``tree_learner.cpp:9-33``)
onto a 1-D named mesh.  The growth loop itself
(:func:`lightgbm_tpu.ops.grow.build_tree`) contains the per-strategy
collectives; this module owns mesh construction, sharding specs, and
the feature-axis padding the block-cyclic layouts need.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import numpy as np

from ..ops.grow import DistConfig, GrowParams, build_tree
from ..utils.log import Log

AXIS_NAME = "shard"


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions (>=0.5 exposes it at the
    top level with ``check_vma``; earlier versions live in
    ``jax.experimental`` with ``check_rep``)."""
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, check_vma=False,
                             in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, check_rep=False, in_specs=in_specs,
               out_specs=out_specs)


def resolve_num_shards(config, mesh=None) -> int:
    """How many ways to shard: an explicit mesh wins; otherwise all
    GLOBAL devices, capped by ``num_machines`` when the user set it.

    When the config carries a reference-style multi-machine topology
    (``machines=`` + ``num_machines>1``, ``config.h:729-744``) and the
    distributed runtime is not up yet, it is initialized here — after
    which ``jax.devices()`` spans every machine.  Initialization
    failures raise; a silent single-node fallback would train at the
    wrong scale."""
    import jax
    if mesh is not None:
        return int(np.prod(mesh.devices.shape))
    machines = getattr(config, "machines", "")
    if not machines and getattr(config, "machine_list_filename", ""):
        with open(config.machine_list_filename) as f:
            machines = f.read()  # newline-separated host:port lines
    if config.num_machines > 1 and machines:
        from .distributed import init_from_machines, is_initialized
        if not is_initialized() and jax.process_count() == 1:
            init_from_machines(machines, config.local_listen_port,
                               config.time_out, config.num_machines)
    n = len(jax.devices())
    if config.num_machines > 1 and jax.process_count() == 1:
        # single-process mesh emulation: num_machines caps the shards
        n = min(n, config.num_machines)
    return n


def make_mesh_for(num_shards: int):
    """A 1-D mesh over the first ``num_shards`` local devices.
    Raises when fewer devices are visible — silently returning a
    narrower mesh than requested is exactly the opaque-placement
    failure mode cross-width resume used to die with (a snapshot
    taken on a wider mesh restores fine on a narrower host; the mesh
    just has to SAY it is narrower — ``docs/Distributed.md``)."""
    import jax
    devices = jax.devices()
    if len(devices) < num_shards:
        raise ValueError(
            f"requested a {num_shards}-shard mesh but only "
            f"{len(devices)} device(s) are visible — pass the real "
            f"device count (resume re-shards checkpointed state to "
            f"any width automatically; see docs/Distributed.md)")
    return jax.sharding.Mesh(np.asarray(devices[:num_shards]),
                             (AXIS_NAME,))


def pad_rows_for(kind: str, num_shards: int, n: int, base: int = 1) -> int:
    """Rows must split evenly over the mesh (and per-shard row count
    must honor the histogram kernel's block size)."""
    step = base if kind in ("feature", "serial", "") \
        else base * num_shards
    return (n + step - 1) // step * step


def pad_features_for(kind: str, num_shards: int, f: int) -> int:
    """Features must split evenly for the feature-block layouts."""
    if kind in ("voting", "serial", ""):
        return f
    d = num_shards
    return (f + d - 1) // d * d


class DistributedBuilder:
    """Callable with :func:`build_tree`'s signature that runs it SPMD.

    Inputs arrive as GLOBAL (host-shaped) arrays; ``jit`` + ``shard_map``
    split them onto the mesh per the learner's specs and reassemble the
    outputs (split records replicated, ``leaf_idx`` row-sharded for the
    data/voting learners).
    """

    def __init__(self, kind: str, params: GrowParams, num_shards: int,
                 mesh=None):
        import jax
        from jax.sharding import PartitionSpec as P

        if kind not in ("data", "feature", "voting"):
            raise ValueError(f"unknown parallel tree_learner {kind!r}")
        self.kind = kind
        self.num_shards = num_shards
        self.mesh = mesh if mesh is not None else make_mesh_for(num_shards)
        if len(self.mesh.axis_names) != 1:
            raise ValueError(
                f"tree learners shard over a 1-D mesh; got axes "
                f"{self.mesh.axis_names}")
        axis = self.mesh.axis_names[0]
        self.params = dataclasses.replace(
            params, dist=DistConfig(kind=kind, axis=axis,
                                    num_shards=num_shards,
                                    top_k=params.dist.top_k))

        S = P(axis)
        R = P()
        if kind == "feature":
            xt_spec, row_spec, feat_spec = P(axis, None), R, S
            leaf_idx_spec = R
        else:  # data | voting: rows sharded, features whole
            xt_spec, row_spec, feat_spec = P(None, axis), S, R
            leaf_idx_spec = S
        # the sharding contract, exposed for (a) mesh-resident placement
        # of the training tensors (device_put once, no per-call
        # resharding) and (b) the fused sharded super-step
        # (models/gbdt.py wraps its K-iteration scan in shard_map with
        # these same specs)
        self.axis = axis
        self.xt_spec, self.row_spec, self.feat_spec = (xt_spec, row_spec,
                                                       feat_spec)

        out_specs = {k: R for k in (
            "leaf", "feature", "threshold", "default_left", "is_cat",
            "gain", "left_stats", "right_stats", "left_mask", "valid",
            "leaf_values", "leaf_values_final", "leaf_stats",
            "n_leaves")}
        if self.params.split.has_monotone:
            for k in ("rec_left_min", "rec_left_max",
                      "rec_right_min", "rec_right_max"):
                out_specs[k] = R
        # mirror build_tree's do_spec predicate exactly: a spec for an
        # absent output is a pytree-structure error at call time
        do_spec = (self.params.speculate > 1 and
                   self.params.use_hist_pool and
                   not self.params.forced and
                   kind in ("data", "feature", "voting") and
                   self.params.wave)
        if do_spec:
            out_specs["n_arm_passes"] = R
        if self.params.quantize:
            out_specs["leaf_stats_exact"] = R
        out_specs["leaf_idx"] = leaf_idx_spec

        def fn(xt, grad, hess, mask, fmask, nb, mt, cat, qk):
            return build_tree(xt, grad, hess, mask, fmask, nb, mt, cat,
                              self.params, quant_key=qk)
        sharded = shard_map_compat(
            fn, self.mesh,
            in_specs=(xt_spec, row_spec, row_spec, row_spec, feat_spec,
                      feat_spec, feat_spec, feat_spec, R),
            out_specs=out_specs)
        self._call = jax.jit(sharded)

    # ------------------------------------------------------------------
    def shardings(self):
        """NamedShardings for the persistent training tensors.  The
        driver ``device_put``s the binned matrix / masks / descriptors
        with these ONCE at construction so every dispatch (per-tree or
        fused super-step) runs on mesh-resident buffers instead of
        re-sharding host-placed arrays per call — the per-shard
        dispatch overhead WEAKSCALE.json measured."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        m = self.mesh
        return {"xt": NamedSharding(m, self.xt_spec),
                "row": NamedSharding(m, self.row_spec),
                "feat": NamedSharding(m, self.feat_spec),
                "rep": NamedSharding(m, P())}

    def pad_rows(self, n: int, base: int = 1) -> int:
        return pad_rows_for(self.kind, self.num_shards, n, base)

    def pad_features(self, f: int) -> int:
        return pad_features_for(self.kind, self.num_shards, f)

    def __call__(self, xt, grad, hess, sample_mask, feature_mask,
                 num_bins, missing_type, is_cat, params=None,
                 quant_key=None):
        import jax
        # params is baked in at construction (signature-compatible with
        # the jitted serial build_tree); reject a drifting override
        # instead of silently training with stale parameters
        if params is not None and \
                dataclasses.replace(params, dist=self.params.dist) != \
                self.params:
            raise ValueError(
                "DistributedBuilder was constructed with different "
                "GrowParams; rebuild the builder to change them")
        if quant_key is None:
            quant_key = jax.random.PRNGKey(0)
        return self._call(xt, grad, hess, sample_mask, feature_mask,
                          num_bins, missing_type, is_cat, quant_key)
