"""Parallel tree-learner builders: ``shard_map`` wrappers around the
device growth loop.

Maps ``tree_learner={data,feature,voting}`` (``tree_learner.cpp:9-33``)
onto a 1-D named mesh, and ``tree_learner=data2d`` onto a 2-D
``Mesh((R, F), ("data", "feature"))`` — rows sharded down one axis,
feature tiles across the other, with the collective schedule factored
per axis (see :mod:`lightgbm_tpu.ops.grow`).  The growth loop itself
(:func:`lightgbm_tpu.ops.grow.build_tree`) contains the per-strategy
collectives; this module owns mesh construction, sharding specs, and
the feature-axis padding the block-cyclic layouts need.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import numpy as np

from ..ops.grow import DistConfig, GrowParams, build_tree
from ..utils.log import Log

AXIS_NAME = "shard"
DATA_AXIS = "data"
FEAT_AXIS = "feature"


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions (>=0.5 exposes it at the
    top level with ``check_vma``; earlier versions live in
    ``jax.experimental`` with ``check_rep``)."""
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, check_vma=False,
                             in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, check_rep=False, in_specs=in_specs,
               out_specs=out_specs)


def resolve_num_shards(config, mesh=None) -> int:
    """How many ways to shard: an explicit mesh wins; otherwise all
    GLOBAL devices, capped by ``num_machines`` when the user set it.

    When the config carries a reference-style multi-machine topology
    (``machines=`` + ``num_machines>1``, ``config.h:729-744``) and the
    distributed runtime is not up yet, it is initialized here — after
    which ``jax.devices()`` spans every machine.  Initialization
    failures raise; a silent single-node fallback would train at the
    wrong scale."""
    import jax
    if mesh is not None:
        return int(np.prod(mesh.devices.shape))
    machines = getattr(config, "machines", "")
    if not machines and getattr(config, "machine_list_filename", ""):
        with open(config.machine_list_filename) as f:
            machines = f.read()  # newline-separated host:port lines
    if config.num_machines > 1 and machines:
        from .distributed import init_from_machines, is_initialized
        if not is_initialized() and jax.process_count() == 1:
            init_from_machines(machines, config.local_listen_port,
                               config.time_out, config.num_machines)
    n = len(jax.devices())
    if config.num_machines > 1 and jax.process_count() == 1:
        # single-process mesh emulation: num_machines caps the shards
        n = min(n, config.num_machines)
    return n


def make_mesh_for(num_shards: int):
    """A 1-D mesh over the first ``num_shards`` local devices.
    Raises when fewer devices are visible — silently returning a
    narrower mesh than requested is exactly the opaque-placement
    failure mode cross-width resume used to die with (a snapshot
    taken on a wider mesh restores fine on a narrower host; the mesh
    just has to SAY it is narrower — ``docs/Distributed.md``)."""
    import jax
    devices = jax.devices()
    if len(devices) < num_shards:
        raise ValueError(
            f"requested a {num_shards}-shard mesh but only "
            f"{len(devices)} device(s) are visible — pass the real "
            f"device count (resume re-shards checkpointed state to "
            f"any width automatically; see docs/Distributed.md)")
    return jax.sharding.Mesh(np.asarray(devices[:num_shards]),
                             (AXIS_NAME,))


def parse_mesh_shape(spec) -> tuple:
    """``'4x2'`` / ``'4,2'`` / ``(4, 2)`` -> ``(4, 2)`` — the
    ``mesh_shape`` config value as a (rows, feature-tiles) pair."""
    if isinstance(spec, (tuple, list)):
        toks = [str(s) for s in spec]
    else:
        import re
        toks = [t for t in re.split(r"[x*,()\s]+", str(spec).strip())
                if t]
    if len(toks) != 2:
        raise ValueError(
            f"mesh_shape must name exactly two axes as 'RxF', got "
            f"{spec!r}")
    r, f = int(toks[0]), int(toks[1])
    if r < 1 or f < 1:
        raise ValueError(f"mesh_shape axes must be positive, got "
                         f"({r}, {f})")
    return (r, f)


def factor_mesh_shape(n: int) -> tuple:
    """Default (R, F) factorization of ``n`` devices when the user set
    ``tree_learner=data2d`` without ``mesh_shape``: the largest
    feature-axis divisor <= sqrt(n), rows get the rest (8 -> 4x2).
    Rows usually outnumber features by orders of magnitude, so the row
    axis gets the larger factor; the feature axis still earns its
    O(1/F_axis) histogram-byte cut."""
    fx = 1
    for d in range(1, int(np.sqrt(n)) + 1):
        if n % d == 0:
            fx = d
    return (n // fx, fx)


def make_mesh_2d(mesh_shape) -> "jax.sharding.Mesh":
    """A 2-D ``(rows, features)`` mesh over the first R*F local
    devices, axes named ``("data", "feature")``.  Raises when fewer
    devices are visible — same no-silent-narrowing contract as
    :func:`make_mesh_for`."""
    import jax
    r, f = (int(s) for s in mesh_shape)
    if r < 1 or f < 1:
        raise ValueError(f"mesh_shape must be positive, got ({r}, {f})")
    need = r * f
    devices = jax.devices()
    if len(devices) < need:
        raise ValueError(
            f"requested a {r}x{f} mesh ({need} devices) but only "
            f"{len(devices)} device(s) are visible — pass a shape the "
            f"host can satisfy (resume re-shards checkpointed state to "
            f"any shape automatically; see docs/Distributed.md)")
    return jax.sharding.Mesh(
        np.asarray(devices[:need]).reshape(r, f), (DATA_AXIS, FEAT_AXIS))


def pad_rows_for(kind: str, num_shards: int, n: int, base: int = 1) -> int:
    """Rows must split evenly over the mesh (and per-shard row count
    must honor the histogram kernel's block size).  ``num_shards`` is
    the ROW-axis size — the 2-D learner passes R, not R*F."""
    step = base if kind in ("feature", "serial", "") \
        else base * num_shards
    return (n + step - 1) // step * step


def pad_features_for(kind: str, num_shards: int, f: int) -> int:
    """Features must split evenly for the feature-block layouts.
    ``num_shards`` is the FEATURE-axis size — the 2-D learner passes
    F, not R*F."""
    if kind in ("voting", "serial", ""):
        return f
    d = num_shards
    return (f + d - 1) // d * d


class DistributedBuilder:
    """Callable with :func:`build_tree`'s signature that runs it SPMD.

    Inputs arrive as GLOBAL (host-shaped) arrays; ``jit`` + ``shard_map``
    split them onto the mesh per the learner's specs and reassemble the
    outputs (split records replicated, ``leaf_idx`` row-sharded for the
    data/voting learners).
    """

    def __init__(self, kind: str, params: GrowParams, num_shards: int,
                 mesh=None, mesh_shape=None, pager=None):
        import jax
        from jax.sharding import PartitionSpec as P

        if kind not in ("data", "feature", "voting", "data2d"):
            raise ValueError(f"unknown parallel tree_learner {kind!r}")
        self.kind = kind
        self.num_shards = num_shards
        R = P()
        if kind == "data2d":
            if mesh is not None:
                if len(mesh.devices.shape) != 2:
                    raise ValueError(
                        f"tree_learner=data2d shards over a 2-D "
                        f"(data, feature) mesh; got axes "
                        f"{mesh.axis_names}")
                shape = tuple(int(s) for s in mesh.devices.shape)
            else:
                shape = tuple(int(s) for s in (
                    mesh_shape if mesh_shape
                    else factor_mesh_shape(num_shards)))
                mesh = make_mesh_2d(shape)
            if shape[0] * shape[1] != num_shards:
                raise ValueError(
                    f"mesh_shape {shape[0]}x{shape[1]} does not factor "
                    f"the {num_shards} shards")
            self.mesh = mesh
            axis, feat_axis = self.mesh.axis_names
            self.row_shards, self.feat_shards = shape
            self.params = dataclasses.replace(
                params, dist=DistConfig(kind=kind, axis=axis,
                                        num_shards=self.row_shards,
                                        top_k=params.dist.top_k,
                                        feat_axis=feat_axis,
                                        feat_shards=self.feat_shards))
            # xt is (F, N): feature tiles down axis 0, row blocks down
            # axis 1 — each device holds an R-th of rows x an F-th of
            # features; descriptors shard with the tiles, per-row state
            # with the row blocks
            xt_spec = P(feat_axis, axis)
            row_spec, feat_spec = P(axis), P(feat_axis)
            leaf_idx_spec = P(axis)
        else:
            self.mesh = mesh if mesh is not None \
                else make_mesh_for(num_shards)
            if len(self.mesh.axis_names) != 1:
                raise ValueError(
                    f"tree learner {kind!r} shards over a 1-D mesh; "
                    f"got axes {self.mesh.axis_names}")
            axis = self.mesh.axis_names[0]
            feat_axis = None
            self.row_shards = num_shards if kind in ("data", "voting") \
                else 1
            self.feat_shards = num_shards if kind == "feature" else 1
            self.params = dataclasses.replace(
                params, dist=DistConfig(kind=kind, axis=axis,
                                        num_shards=num_shards,
                                        top_k=params.dist.top_k))

            S = P(axis)
            if kind == "feature":
                xt_spec, row_spec, feat_spec = P(axis, None), R, S
                leaf_idx_spec = R
            else:  # data | voting: rows sharded, features whole
                xt_spec, row_spec, feat_spec = P(None, axis), S, R
                leaf_idx_spec = S
        # the sharding contract, exposed for (a) mesh-resident placement
        # of the training tensors (device_put once, no per-call
        # resharding) and (b) the fused sharded super-step
        # (models/gbdt.py wraps its K-iteration scan in shard_map with
        # these same specs)
        self.axis = axis
        self.feat_axis = feat_axis
        self.xt_spec, self.row_spec, self.feat_spec = (xt_spec, row_spec,
                                                       feat_spec)

        out_specs = {k: R for k in (
            "leaf", "feature", "threshold", "default_left", "is_cat",
            "gain", "left_stats", "right_stats", "left_mask", "valid",
            "leaf_values", "leaf_values_final", "leaf_stats",
            "n_leaves")}
        if self.params.split.has_monotone:
            for k in ("rec_left_min", "rec_left_max",
                      "rec_right_min", "rec_right_max"):
                out_specs[k] = R
        # mirror build_tree's do_spec predicate exactly: a spec for an
        # absent output is a pytree-structure error at call time
        do_spec = (self.params.speculate > 1 and
                   self.params.use_hist_pool and
                   not self.params.forced and
                   kind in ("data", "feature", "voting") and
                   self.params.wave)
        if do_spec:
            out_specs["n_arm_passes"] = R
        if self.params.quantize:
            out_specs["leaf_stats_exact"] = R
        out_specs["leaf_idx"] = leaf_idx_spec

        # device-block pager (io/pager.py): the per-tree dispatch
        # substitutes the PagedXt view for the sharded xt operand —
        # the slot keeps a replicated dummy so the call signature
        # stays build_tree's, and each program instance pages its own
        # (f_loc, n_loc) block through axis-indexed callbacks
        self.pager_view = pager.view(kind, axis, feat_axis) \
            if pager is not None else None
        view = self.pager_view
        if view is not None:
            xt_spec = R

        def fn(xt, grad, hess, mask, fmask, nb, mt, cat, qk):
            if view is not None:
                # trace-time operand swap; build_tree_impl runs
                # un-jitted here because the whole shard_map is
                # already under jit and PagedXt is not a pytree leaf
                from ..ops.grow import build_tree_impl
                return build_tree_impl(view, grad, hess, mask, fmask,
                                       nb, mt, cat, self.params,
                                       quant_key=qk)
            return build_tree(xt, grad, hess, mask, fmask, nb, mt, cat,
                              self.params, quant_key=qk)
        sharded = shard_map_compat(
            fn, self.mesh,
            in_specs=(xt_spec, row_spec, row_spec, row_spec, feat_spec,
                      feat_spec, feat_spec, feat_spec, R),
            out_specs=out_specs)
        self._call = jax.jit(sharded)

    # ------------------------------------------------------------------
    def shardings(self):
        """NamedShardings for the persistent training tensors.  The
        driver ``device_put``s the binned matrix / masks / descriptors
        with these ONCE at construction so every dispatch (per-tree or
        fused super-step) runs on mesh-resident buffers instead of
        re-sharding host-placed arrays per call — the per-shard
        dispatch overhead WEAKSCALE.json measured."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        m = self.mesh
        return {"xt": NamedSharding(m, self.xt_spec),
                "row": NamedSharding(m, self.row_spec),
                "feat": NamedSharding(m, self.feat_spec),
                "rep": NamedSharding(m, P())}

    def pad_rows(self, n: int, base: int = 1) -> int:
        return pad_rows_for(self.kind, max(self.row_shards, 1), n, base)

    def pad_features(self, f: int) -> int:
        shards = self.feat_shards if self.kind == "data2d" \
            else self.num_shards
        return pad_features_for(self.kind, shards, f)

    def __call__(self, xt, grad, hess, sample_mask, feature_mask,
                 num_bins, missing_type, is_cat, params=None,
                 quant_key=None):
        import jax
        # params is baked in at construction (signature-compatible with
        # the jitted serial build_tree); reject a drifting override
        # instead of silently training with stale parameters
        if params is not None and \
                dataclasses.replace(params, dist=self.params.dist) != \
                self.params:
            raise ValueError(
                "DistributedBuilder was constructed with different "
                "GrowParams; rebuild the builder to change them")
        if quant_key is None:
            quant_key = jax.random.PRNGKey(0)
        return self._call(xt, grad, hess, sample_mask, feature_mask,
                          num_bins, missing_type, is_cat, quant_key)
