"""Multi-process / multi-host initialization.

Reference: the socket linker builds an N x N TCP mesh from ``machines``
(``src/network/linkers_socket.cpp:163-224``, config keys
``machines`` / ``machine_list_filename`` / ``local_listen_port`` /
``num_machines``, ``config.h:729-744``); the MPI linker uses the MPI
world (``linkers_mpi.cpp``).  Collectives then run over that mesh
(Allreduce / ReduceScatter / Allgather, ``network.h:96``).

TPU-native redesign: processes join a JAX distributed runtime
(``jax.distributed.initialize``) — the coordinator is machine 0 — and
the collectives are XLA collectives over the GLOBAL device mesh that
``jax.devices()`` exposes afterwards; there is no hand-rolled socket
protocol to maintain and the traffic rides ICI/DCN as XLA schedules it.
The reference's "which machine am I" discovery (matching local
interfaces against the machine list) is mirrored here, with an explicit
``LTPU_MACHINE_RANK`` escape hatch for containers whose interface
addresses do not match the advertised list.

A failed or inconsistent initialization RAISES.  It must never degrade
to single-node silently: a distributed caller would train on 1/N of the
data at full learning rate and get a wrong-scale model (round-2
verdict, weak #9).
"""
from __future__ import annotations

import os
import socket
from typing import List, Optional, Tuple

from ..utils.log import Log

__all__ = ["init_from_machines", "init_distributed", "shutdown",
           "is_initialized", "process_info"]

_state = {"initialized": False, "num_processes": 1, "process_id": 0}


def _parse_machines(machines: str) -> List[Tuple[str, int]]:
    """``ip1:port1,ip2:port2`` -> [(host, port), ...] — the reference's
    machine-list format (``config.h:729``; ``Network::Init`` splits on
    ',' then ':')."""
    out: List[Tuple[str, int]] = []
    for tok in machines.replace("\n", ",").split(","):
        tok = tok.strip()
        if not tok:
            continue
        if ":" in tok:
            host, port = tok.rsplit(":", 1)
            out.append((host.strip(), int(port)))
        else:
            out.append((tok, 0))
    return out


def _local_addresses() -> List[str]:
    addrs = {"localhost", "127.0.0.1"}
    try:
        hostname = socket.gethostname()
        addrs.add(hostname)
        for info in socket.getaddrinfo(hostname, None):
            addrs.add(info[4][0])
    except OSError:
        pass
    return list(addrs)


def _find_rank(nodes: List[Tuple[str, int]],
               local_listen_port: int) -> Optional[int]:
    """Which entry of the machine list is THIS process?  Mirrors the
    reference's own-address scan (``linkers_socket.cpp:TryBind`` loop),
    disambiguating same-host entries by ``local_listen_port``."""
    env = os.environ.get("LTPU_MACHINE_RANK")
    if env is not None:
        return int(env)
    local = set(_local_addresses())
    matches = [i for i, (host, port) in enumerate(nodes)
               if host in local and
               (local_listen_port <= 0 or port == local_listen_port or
                port == 0)]
    if len(matches) == 1:
        return matches[0]
    if len(matches) > 1:
        # several same-host entries: the port must decide
        exact = [i for i in matches if nodes[i][1] == local_listen_port]
        if len(exact) == 1:
            return exact[0]
    return None


def init_from_machines(machines: str, local_listen_port: int,
                       listen_time_out: int, num_machines: int) -> None:
    """Join the distributed runtime described by a reference-style
    machine list (``LGBM_NetworkInit`` / CLI ``machines=`` contract)."""
    if num_machines <= 1:
        return
    nodes = _parse_machines(machines)
    if len(nodes) < num_machines:
        raise ValueError(
            f"machines lists {len(nodes)} nodes but num_machines="
            f"{num_machines}")
    rank = _find_rank(nodes[:num_machines], local_listen_port)
    if rank is None:
        raise RuntimeError(
            "cannot determine this process's rank from the machine "
            "list; set LTPU_MACHINE_RANK=<index> explicitly "
            f"(machines={machines!r})")
    host, port = nodes[0]
    coordinator = f"{host}:{port if port > 0 else 12355}"
    init_distributed(coordinator, num_machines, rank,
                     timeout_s=listen_time_out * 60 if listen_time_out
                     else None)


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int, timeout_s: Optional[int] = None
                     ) -> None:
    """``jax.distributed.initialize`` wrapper; afterwards
    ``jax.devices()`` is the GLOBAL device list and the parallel tree
    learners' meshes span every machine."""
    import jax

    if _state["initialized"]:
        if (_state["num_processes"], _state["process_id"]) != \
                (num_processes, process_id):
            raise RuntimeError("distributed runtime already initialized "
                               "with a different topology")
        return
    kwargs = {}
    if timeout_s:
        kwargs["initialization_timeout"] = timeout_s
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)
    _state.update(initialized=True, num_processes=num_processes,
                  process_id=process_id)
    Log.info("distributed runtime up: process %d/%d, %d global devices",
             process_id, num_processes, len(jax.devices()))


def shutdown() -> None:
    if not _state["initialized"]:
        return
    import jax
    try:
        jax.distributed.shutdown()
    finally:
        _state.update(initialized=False, num_processes=1, process_id=0)


def is_initialized() -> bool:
    return bool(_state["initialized"])


def process_info() -> Tuple[int, int]:
    """(process_id, num_processes) of the joined runtime."""
    return _state["process_id"], _state["num_processes"]
