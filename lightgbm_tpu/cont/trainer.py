"""The continual training daemon: a preemption-safe, self-healing
ingest -> validate -> train -> checkpoint -> publish loop.

``ContinualTrainer`` closes ROADMAP item 5: it composes the pieces the
repo already has — warm-start continue-training (PR 1/3, bit-exact
mid-bagging-cycle), atomic bit-exact checkpoints (PR 5,
``ckpt/manager.py``), and the validated auto-publish + rollback tier
(PR 6, ``serve/watcher.py``) — into one long-running trainer that
survives the failure modes a days-long run on preemptible TPUs
actually meets:

- **bad input**: every batch passes the :class:`~.validate.
  BatchValidator` gates (schema, non-finite, label/feature drift);
  rejects are MOVED to quarantine and accounted in telemetry.
- **corrupted-past-validation input**: the numerical-health guard
  (``utils/health.py``) trips inside training — fused blocks carry a
  per-iteration finiteness flag in their packed fetch — the batch's
  in-flight checkpoints are pruned (``CheckpointManager.prune_after``)
  and the model rewinds exactly to the pre-batch boundary.
- **wedged steps**: a per-iteration heartbeat feeds the stall
  watchdog; a step silent past ``continual_stall_timeout_s`` is
  abandoned (its thread unblocks and exits via the attempt-generation
  token) and the batch retries from the last snapshot, bounded by
  ``continual_max_batch_retries`` before quarantine.
- **preemption**: SIGTERM/SIGINT raise the process-wide flag
  (``engine.request_preempt``); the in-flight batch checkpoints at
  the next served boundary (``reason=preempt``) and the daemon drains.
  Restart resumes the interrupted batch BIT-exactly (PR 5 resume), so
  the final model equals an uninterrupted run over the same surviving
  batches.
- **crash (SIGKILL)**: nothing graceful runs — the atomic checkpoint
  protocol plus the ledger (``continual_state.json``, written with the
  same tmp+rename discipline) make restart land on the newest valid
  snapshot and re-enter the interrupted batch.

The checkpoint root is also the PUBLISH root: the serve tier's
``CheckpointWatcher`` (same process or another) manifest-verifies and
canary-scores every finalized snapshot before it can serve traffic, so
the daemon never needs to be trusted — only its checkpoints do.

Fault-injection points (``utils/faults.py``): ``ingest.read``,
``ingest.validate`` (in ``source.py``/``validate.py``),
``trainer.step`` (per boosting iteration: ``error`` | ``hang`` |
``sleep_<ms>``) and ``trainer.refit`` (``error``).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from .. import engine as engine_mod
from ..basic import Booster, Dataset
from ..ckpt import CheckpointManager
from ..config import Config
from ..obs import flight as _flight
from ..obs import spans as _spans
from ..serve.registry import model_fingerprint
from ..utils import faults as _faults
from ..utils import telemetry as _telemetry
from ..utils.health import NumericalHealthError
from ..utils.log import Log
from .config import ContinualConfig
from .source import Batch, BatchSource, DirectoryBatchSource
from .validate import BatchValidator

__all__ = ["ContinualTrainer"]

# engine.train pops these from params and lets them OVERRIDE its
# num_boost_round argument — the daemon owns the per-batch round
# budget, so they must not leak into the engine params
_ROUND_ALIASES = ("num_iterations", "num_iteration", "n_iter",
                  "num_tree", "num_trees", "num_round", "num_rounds",
                  "num_boost_round", "n_estimators", "max_iter")


def _fingerprint(text: Optional[str]) -> str:
    """Content identity of a model text — the serve tier's ONE
    definition (``model_id`` on published versions), so the ledger
    correlates directly with watcher/loadgen output."""
    return "" if not text else model_fingerprint(text)


class _Heartbeat:
    """Monotonic last-sign-of-life timestamp (GIL-atomic float).
    ``steps`` counts iteration-boundary beats: until the SECOND one,
    the attempt is still inside its first iteration — which pays the
    full per-booster XLA compile — and the stall watchdog applies a
    grace multiple instead of reading warmup as a wedge."""

    def __init__(self):
        self.t = time.monotonic()
        self.steps = 0

    def beat(self, step: bool = False) -> None:
        self.t = time.monotonic()
        if step:
            self.steps += 1

    def age(self) -> float:
        return time.monotonic() - self.t


class ContinualTrainer:
    """Drive the continual loop.  ``run()`` blocks until preempted,
    stopped, ``continual_max_batches`` consumed, or idle past
    ``continual_idle_exit_s``; it may run on any thread (tests drive
    it inline, the CLI runs it under a main-thread preempt guard)."""

    def __init__(self, params: Dict[str, Any],
                 config: Optional[ContinualConfig] = None,
                 source: Optional[BatchSource] = None,
                 validator: Optional[BatchValidator] = None,
                 recorder=None):
        self.params = dict(params)
        cfg = Config(self.params)
        self.cont = config or ContinualConfig.from_params(cfg)
        self.cont.validate()
        # obs_flight_recorder=true arms the process-wide anomaly
        # capture ring (obs/flight.py) for the whole daemon lifetime
        _flight.ensure_installed(cfg)
        self.root = str(cfg.checkpoint_dir or "")
        if not self.root:
            raise ValueError("continual training requires "
                             "checkpoint_dir (the checkpoint root is "
                             "also the publish root)")
        self.keep_last_n = max(int(cfg.keep_last_n or 2), 2)
        self.refit_decay = float(cfg.refit_decay_rate)
        # streamed per-batch ingest (docs/Streaming.md): resolved
        # through Config so the registered aliases (stream,
        # out_of_core) work like everywhere else
        self._stream_batches = bool(getattr(cfg, "stream_ingest",
                                            False))
        self._stream_cache_dir = str(
            getattr(cfg, "stream_cache_dir", "") or
            os.path.join(self.root, "_stream_cache"))
        self.recorder = recorder
        self.mgr = CheckpointManager(self.root, self.keep_last_n,
                                     recorder)
        self.source = source or DirectoryBatchSource(
            self.cont.ingest_dir,
            quarantine_dir=self.cont.resolved_quarantine_dir(),
            processed_dir=self.cont.resolved_processed_dir(),
            read_retries=self.cont.read_retries,
            backoff_base_s=self.cont.backoff_base_s,
            backoff_max_s=self.cont.backoff_max_s,
            recorder=recorder)
        self.validator = validator or BatchValidator(
            drift_sigma=self.cont.drift_sigma,
            range_factor=self.cont.range_factor,
            nonfinite_check=self.cont.nonfinite_check)
        self.ledger_path = os.path.join(self.root,
                                        "continual_state.json")
        self._model_text: Optional[str] = None
        self._model_iter = 0
        self._batches_done = 0
        self._inflight: Optional[Dict[str, Any]] = None
        self._stop = threading.Event()
        self._gen_lock = threading.Lock()
        self._generation = 0
        self.stats: Dict[str, Any] = {
            "batches": 0, "rows": 0, "quarantined": 0,
            "stall_restarts": 0, "nonfinite_rewinds": 0,
            "batch_errors": 0, "refits": 0, "status": "",
        }

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------
    def _emit(self, event: str, **fields) -> None:
        _telemetry.counters.incr(f"continual_{event}s")
        rec = self.recorder or _telemetry.get_recorder()
        if rec is not None:
            rec.emit("continual", event=event, **fields)

    def _engine_params(self) -> Dict[str, Any]:
        eng = dict(self.params)
        for key in _ROUND_ALIASES + ("resume_from", "resume",
                                     "resume_checkpoint"):
            eng.pop(key, None)
        # the shared recorder (telemetry.set_recorder) replaces
        # per-batch telemetry files: one JSONL stream, one file handle
        eng.pop("telemetry_file", None)
        eng["checkpoint_dir"] = self.root
        eng["keep_last_n"] = self.keep_last_n
        eng["snapshot_freq"] = self.cont.snapshot_freq \
            if self.cont.snapshot_freq > 0 else -1
        return eng

    def _make_dataset(self, batch: Batch,
                      eng_params: Dict[str, Any]) -> Dataset:
        kw: Dict[str, Any] = {}
        if batch.weight is not None:
            kw["weight"] = np.asarray(batch.weight)
        if batch.group is not None:
            kw["group"] = np.asarray(batch.group)
        if self._stream_batches:
            # out-of-core batches (docs/Streaming.md): construction
            # routes through the crash-safe binned cache, so a daemon
            # restart mid-batch re-ingests the SAME content key and
            # reuses the fit mappers + every published chunk instead
            # of re-binning — the BatchSource seam's resume contract.
            # mmap-pair shards stay on disk end to end.
            params = dict(eng_params)
            params["stream_cache_dir"] = self._stream_cache_dir
            return Dataset(batch.X, label=np.asarray(batch.y),
                           params=params, **kw)
        return Dataset(np.ascontiguousarray(np.asarray(batch.X)),
                       label=np.asarray(batch.y),
                       params=dict(eng_params), **kw)

    # -- ledger --------------------------------------------------------
    def _write_ledger(self) -> None:
        data = {
            "schema": 1,
            "batches_done": int(self._batches_done),
            "model_iter": int(self._model_iter),
            "model_fingerprint": _fingerprint(self._model_text),
            "inflight": self._inflight,
            "validator": self.validator.state(),
        }
        tmp = self.ledger_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.ledger_path)

    def _read_ledger(self) -> Dict[str, Any]:
        try:
            with open(self.ledger_path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return {}
        return data if isinstance(data, dict) else {}

    def _sync_from_checkpoints(self) -> None:
        """Adopt the newest VALID checkpoint as the current model —
        the restart (and rewind-fallback) recovery path."""
        self._model_text, self._model_iter = None, 0
        for iter_, path in reversed(self.mgr.candidates()):
            if CheckpointManager.validate(path):
                continue               # corrupt: the loader's fallback
            try:
                with open(os.path.join(path, "model.txt")) as f:
                    self._model_text = f.read()
                self._model_iter = int(iter_)
                return
            except OSError:            # pragma: no cover - torn dir
                continue

    def bootstrap(self) -> None:
        """Recover daemon state after a restart: ledger + newest valid
        checkpoint + the in-flight batch (if its files survived)."""
        os.makedirs(self.root, exist_ok=True)
        ledger = self._read_ledger()
        self._batches_done = int(ledger.get("batches_done", 0))
        self.validator.restore_state(ledger.get("validator"))
        self._sync_from_checkpoints()
        inflight = ledger.get("inflight")
        if inflight and inflight.get("batch") in self.source.pending():
            self._inflight = dict(inflight)
            self._emit("resume", batch=inflight["batch"],
                       start_iter=int(inflight.get("start_iter", 0)),
                       model_iter=self._model_iter)
            Log.info("continual: resuming in-flight batch %s (model "
                     "at iteration %d)", inflight["batch"],
                     self._model_iter)
        else:
            self._inflight = None

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------
    def stop(self) -> None:
        """Programmatic drain (tests/benchmarks): finish the in-flight
        batch boundary and exit the loop."""
        self._stop.set()

    def _stopping(self) -> Optional[str]:
        if self._stop.is_set():
            return "stopped"
        if engine_mod.preempt_requested() is not None:
            return "preempt"
        return None

    def _sleep(self, seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline and self._stopping() is None:
            time.sleep(min(0.1, seconds))

    def run(self) -> Dict[str, Any]:
        self.bootstrap()
        prev_recorder = _telemetry.get_recorder()
        if self.recorder is not None and prev_recorder is None:
            # per-batch boosters adopt the daemon's recorder (one
            # stream for the whole loop; models/gbdt.py fallback)
            _telemetry.set_recorder(self.recorder)
        last_activity = time.monotonic()
        status = "idle_exit"
        try:
            while True:
                stop = self._stopping()
                if stop is not None:
                    if stop == "preempt":
                        self._emit("preempt",
                                   signum=int(
                                       engine_mod.preempt_requested()))
                    status = stop
                    break
                if self.cont.max_batches and \
                        self.stats["batches"] >= self.cont.max_batches:
                    status = "max_batches"
                    break
                q_before = getattr(self.source, "quarantined", 0)
                batch = self.source.next_batch()
                if batch is None:
                    if getattr(self.source, "quarantined", 0) != \
                            q_before:
                        # an unreadable file was quarantined: that is
                        # activity, and the NEXT file may be fine
                        last_activity = time.monotonic()
                        continue
                    if self.cont.idle_exit_s > 0 and \
                            time.monotonic() - last_activity > \
                            self.cont.idle_exit_s:
                        self._emit("idle_exit")
                        status = "idle_exit"
                        break
                    self._sleep(self.cont.poll_s)
                    continue
                last_activity = time.monotonic()
                st = self._consume(batch)
                if st == "preempt":
                    self._emit("preempt", batch=batch.name,
                               model_iter=self._model_iter)
                    status = "preempt"
                    break
        finally:
            self._write_ledger()
            self.stats["quarantined"] = \
                int(getattr(self.source, "quarantined", 0))
            if self.recorder is not None and prev_recorder is None:
                _telemetry.set_recorder(None)
        self.stats["status"] = status
        Log.info("continual: loop ended (%s): %d batches, %d "
                 "quarantined, %d stall restarts, %d non-finite "
                 "rewinds, model at iteration %d", status,
                 self.stats["batches"], self.stats["quarantined"],
                 self.stats["stall_restarts"],
                 self.stats["nonfinite_rewinds"], self._model_iter)
        return dict(self.stats)

    # ------------------------------------------------------------------
    # one batch
    # ------------------------------------------------------------------
    def _consume(self, batch: Batch) -> str:
        # one TRACE per batch, rooted here (obs/spans.py): ingest ->
        # validate -> train -> checkpoint happen under it, the
        # checkpoint carries it to the watcher, the watcher to the
        # fleet publish and the first served request — one joinable
        # timeline across processes (tools/trace_view.py)
        rec = self.recorder or _telemetry.get_recorder()
        with _spans.span("batch", recorder=rec, root=True,
                         announce=True, task="continual",
                         batch=batch.name, rows=batch.rows) as sp:
            with _spans.span("validate", recorder=rec,
                             batch=batch.name):
                errs = self.validator.check(batch)
            if errs:
                self.source.quarantine(batch, "validate",
                                       "; ".join(errs)[:300])
                sp.set(outcome="quarantined")
                return "quarantined"
            out = self._train_batch(batch)
            sp.set(outcome=out)
            return out

    def _next_is_refit(self) -> bool:
        return (self.cont.refit_every > 0 and
                self._model_text is not None and
                (self._batches_done + 1) % self.cont.refit_every == 0)

    def _train_batch(self, batch: Batch) -> str:
        t_batch0 = time.perf_counter()
        if self._inflight is not None and \
                self._inflight.get("batch") == batch.name:
            # restart continuation of an interrupted batch
            start_iter = int(self._inflight.get("start_iter",
                                                self._model_iter))
            refit = bool(self._inflight.get("refit", False))
            pre_fp = str(self._inflight.get("pre_fingerprint", ""))
            if refit and pre_fp and \
                    _fingerprint(self._model_text) != pre_fp:
                # the refit re-save landed before the crash: redoing
                # it would decay the leaf values twice
                self._finish_batch(batch, "refit", start_iter, t_batch0)
                return "done"
        else:
            start_iter = self._model_iter
            refit = self._next_is_refit()
            self._inflight = {
                "batch": batch.name,
                "start_iter": int(start_iter),
                "refit": bool(refit),
                "pre_fingerprint": _fingerprint(self._model_text),
            }
            self._write_ledger()
        target_iter = start_iter + \
            (0 if refit else self.cont.rounds_per_batch)
        pre_text, pre_iter = self._model_text, start_iter

        attempt = 0
        while True:
            attempt += 1
            with self._gen_lock:
                self._generation += 1
                gen = self._generation

            def alive(g=gen):
                with self._gen_lock:
                    return self._generation == g
            hb = _Heartbeat()
            box: Dict[str, Any] = {}
            th = threading.Thread(
                target=self._run_attempt,
                args=(batch, refit, start_iter, target_iter, box, hb,
                      alive, _spans.current()),
                name=f"ltpu-continual-{batch.name}", daemon=True)
            th.start()
            stalled = False
            while th.is_alive():
                th.join(0.1)
                limit = self.cont.stall_timeout_s
                if limit > 0 and hb.steps < 2:
                    # first iteration of a fresh per-batch booster:
                    # the fused scan (or first tree program) compiles
                    # here, and compile time is not a wedge
                    limit *= 5
                if limit > 0 and hb.age() > limit:
                    stalled = True
                    break
            if stalled:
                with self._gen_lock:
                    self._generation += 1   # the zombie sees !alive()
                self.stats["stall_restarts"] += 1
                self._emit("stall_restart", batch=batch.name,
                           attempt=attempt,
                           stalled_s=round(hb.age(), 3))
                Log.warning("continual: train step on %s stalled "
                            "(%.1fs without a heartbeat, attempt "
                            "%d/%d) — abandoning the attempt and "
                            "restarting from the last snapshot",
                            batch.name, hb.age(), attempt,
                            self.cont.max_batch_retries + 1)
                if attempt > self.cont.max_batch_retries:
                    return self._quarantine_batch(
                        batch, "stall", pre_text, pre_iter,
                        f"stalled {attempt} attempt(s)")
                self._sync_from_checkpoints()
                if self._refit_already_landed(refit):
                    self._finish_batch(batch, "refit", start_iter,
                                       t_batch0)
                    return "done"
                continue
            err = box.get("error")
            if err is None:
                self._model_text = box["model_text"]
                self._model_iter = int(box["iter"])
                if engine_mod.preempt_requested() is not None and \
                        self._model_iter < target_iter:
                    # the engine checkpointed at the preempt boundary
                    # and returned early: the batch stays in the
                    # ingest dir (and in the ledger) for the restarted
                    # daemon to resume bit-exactly
                    self._write_ledger()
                    return "preempt"
                self._finish_batch(batch,
                                   "refit" if refit else "extend",
                                   start_iter, t_batch0)
                return "done"
            if isinstance(err, NumericalHealthError):
                self.stats["nonfinite_rewinds"] += 1
                return self._quarantine_batch(
                    batch, "nonfinite", pre_text, pre_iter, str(err))
            self.stats["batch_errors"] += 1
            self._emit("batch_error", batch=batch.name,
                       attempt=attempt, error=str(err)[:300])
            Log.warning("continual: train attempt %d/%d on %s failed: "
                        "%s", attempt, self.cont.max_batch_retries + 1,
                        batch.name, err)
            if attempt > self.cont.max_batch_retries:
                return self._quarantine_batch(batch, "error", pre_text,
                                              pre_iter, str(err))
            self._sync_from_checkpoints()
            if self._refit_already_landed(refit):
                self._finish_batch(batch, "refit", start_iter,
                                   t_batch0)
                return "done"

    def _finish_batch(self, batch: Batch, mode: str, start_iter: int,
                      t_batch0: float) -> None:
        # fold the batch into the drift reference BEFORE the ledger
        # write below persists validator.state() — a crash after
        # mark_done must not leave a processed batch permanently
        # missing from the restart's baseline
        self.validator.observe(batch)
        self.source.mark_done(batch)
        self._inflight = None
        self._batches_done += 1
        self.stats["batches"] += 1
        self.stats["rows"] += batch.rows
        if mode == "refit":
            self.stats["refits"] += 1
        self._write_ledger()
        if self._stream_batches:
            # retention for per-batch binned caches: a finished batch
            # no longer needs its cache (only the INFLIGHT batch's
            # restart does); keep a small tail for producers that
            # replay recent shards
            from ..io import stream as stream_mod
            stream_mod.prune_cache_root(self._stream_cache_dir,
                                        keep_last=2)
        self._emit("batch", batch=batch.name, rows=batch.rows,
                   mode=mode, iter=int(self._model_iter),
                   start_iter=int(start_iter),
                   duration_ms=round(
                       (time.perf_counter() - t_batch0) * 1e3, 3))
        Log.info("continual: batch %s done (%s, %d rows, model at "
                 "iteration %d)", batch.name, mode, batch.rows,
                 self._model_iter)

    def _refit_already_landed(self, refit: bool) -> bool:
        """After a stall/error retry resynced from checkpoints: did
        the abandoned attempt's refit re-save already land?  Re-running
        the refit would apply the leaf decay twice (the same guard the
        crash-restart path applies via the ledger fingerprint)."""
        if not refit or self._inflight is None:
            return False
        pre_fp = str(self._inflight.get("pre_fingerprint", ""))
        return bool(pre_fp) and _fingerprint(self._model_text) != pre_fp

    def _quarantine_batch(self, batch: Batch, reason: str,
                          pre_text: Optional[str], pre_iter: int,
                          detail: str) -> str:
        """Exact rewind + quarantine: the batch's in-flight snapshots
        leave the lineage so a restart (or the next batch) continues
        from state the surviving batches produced."""
        self.mgr.prune_after(pre_iter)
        if pre_text is not None:
            self._model_text, self._model_iter = pre_text, pre_iter
        else:
            self._sync_from_checkpoints()
        self.source.quarantine(batch, reason, detail[:300])
        self._inflight = None
        self._write_ledger()
        return "quarantined"

    # ------------------------------------------------------------------
    # one training attempt (worker thread)
    # ------------------------------------------------------------------
    def _step_callback(self, hb: _Heartbeat, alive):
        def cb(env):
            if not alive():
                # the watchdog abandoned this attempt and a retry owns
                # the checkpoint root now: a recovered-but-slow zombie
                # must stop at its next boundary instead of racing the
                # retry's snapshot writes
                raise RuntimeError("attempt abandoned by the stall "
                                   "watchdog")
            hb.beat(step=True)
            mode = _faults.fire("trainer.step")
            if mode == "error":
                raise RuntimeError("injected fault "
                                   "(trainer.step:error)")
            if mode == "hang":
                # block until the watchdog abandons this attempt; the
                # generation token unblocks the zombie so it exits
                # instead of sleeping forever
                while alive():
                    time.sleep(0.05)
                raise RuntimeError("stalled step abandoned by the "
                                   "watchdog")
            if mode.startswith("sleep_"):
                time.sleep(float(mode[len("sleep_"):]) / 1e3)
        cb.before_iteration = True
        cb.order = -100
        return cb

    def _run_attempt(self, batch: Batch, refit: bool, start_iter: int,
                     target_iter: int, box: Dict[str, Any],
                     hb: _Heartbeat, alive, carrier=None) -> None:
        try:
            # contextvars do not flow into thread targets: re-enter
            # the batch trace so engine.train's 'train' span (and the
            # checkpoint saves, whose extra.json carries the context
            # to the watcher) parent under the batch root
            with _spans.use(carrier):
                eng = self._engine_params()
                hb.beat()
                if refit:
                    self._refit_attempt(batch, eng, start_iter, box,
                                        hb)
                    return
                ds = self._make_dataset(batch, eng)
                hb.beat()
                nv = self._newest_valid_iter()
                resume = nv is not None and nv > start_iter
                kw: Dict[str, Any] = {}
                init_model = None
                if resume:
                    # mid-batch snapshot exists (preempt/crash/stall):
                    # continue BIT-exactly from it; num_boost_round is
                    # the absolute target under resume
                    kw["resume_from"] = "auto"
                    rounds = target_iter
                else:
                    rounds = target_iter - start_iter
                    if self._model_text is not None:
                        init_model = Booster(
                            model_str=self._model_text)
                bst = engine_mod.train(
                    eng, ds, num_boost_round=rounds,
                    init_model=init_model,
                    callbacks=[self._step_callback(hb, alive)],
                    verbose_eval=False, **kw)
                if not alive():
                    return             # abandoned: result is stale
                box["model_text"] = bst.model_to_string(
                    num_iteration=-1)
                box["iter"] = int(bst._gbdt.completed_iterations())
        except NumericalHealthError as exc:
            box["error"] = exc
        except BaseException as exc:       # noqa: BLE001 - the loop
            box["error"] = exc             # owns the failure taxonomy

    def _refit_attempt(self, batch: Batch, eng: Dict[str, Any],
                       start_iter: int, box: Dict[str, Any],
                       hb: _Heartbeat) -> None:
        mode = _faults.fire("trainer.refit")
        if mode == "error":
            raise RuntimeError("injected fault (trainer.refit:error)")
        donor = Booster(model_str=self._model_text)
        hb.beat()
        donor.refit(np.asarray(batch.X), np.asarray(batch.y),
                    weight=None if batch.weight is None
                    else np.asarray(batch.weight),
                    decay_rate=self.refit_decay)
        hb.beat()
        refit_text = donor.model_to_string(num_iteration=-1)
        bad = [float(v) for t in donor._gbdt.models
               for v in t.leaf_value[:max(t.num_leaves, 1)]
               if not np.isfinite(v)]
        if bad:
            raise NumericalHealthError(start_iter, "refit",
                                       f"{len(bad)} non-finite leaf "
                                       f"value(s) after refit")
        # re-seed a TRAINING booster on the batch so the checkpoint
        # carries a model-consistent score/RNG state (refit mutates
        # leaf values in place; the donor's replayed score is stale)
        ds = self._make_dataset(batch, eng)
        bst = Booster(params=eng, train_set=ds)
        bst._gbdt.init_from_model(donor._gbdt.models, ds.raw_mat)
        hb.beat()
        self.mgr.save(bst, reason="refit")
        box["model_text"] = refit_text
        box["iter"] = int(start_iter)

    def _newest_valid_iter(self) -> Optional[int]:
        for iter_, path in reversed(self.mgr.candidates()):
            if not CheckpointManager.validate(path):
                return int(iter_)
        return None
