"""Batch validation gates for the continual training daemon.

Every batch passes through this pipeline BEFORE it can touch the
model; a daemon that trains for days lives on the principle that bad
input is quarantined at the door, not discovered as a NaN model at
serve time.  Gates, in order:

1. **schema/dtype/shape** — X is a non-empty 2-D numeric matrix, y a
   matching 1-D numeric vector, optional weight/group consistent
   (group sums to the row count), and the feature width matches the
   reference established by previously-accepted batches.
2. **non-finite scan** — NaN/inf anywhere in X or y fails the batch
   (``continual_nonfinite_check``; the in-training numerical-health
   guard, ``utils/health.py``, remains the backstop when this gate is
   disabled or the corruption happens downstream of it).
3. **label-distribution drift** — the batch's label mean must lie
   within ``continual_drift_sigma`` reference standard deviations of
   the running reference (Welford over all accepted rows); a feed that
   silently flips its label convention fails here, not in production.
4. **feature-range drift** — batch values outside the reference
   per-feature min/max inflated by ``continual_range_factor`` x span
   fail (a unit change — meters to millimeters — is drift, not noise).

``check`` returns the problem list (empty = accept); ``observe``
folds an ACCEPTED batch into the running reference.  The reference
state round-trips through ``state()``/``restore_state()`` so a daemon
restart keeps its drift baseline (the ledger carries it).

Fault-injection point: ``ingest.validate`` (mode ``reject``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import faults as _faults

__all__ = ["BatchValidator"]


class BatchValidator:
    """Stateful validation pipeline with a running drift reference."""

    def __init__(self, drift_sigma: float = 8.0,
                 range_factor: float = 10.0,
                 nonfinite_check: bool = True,
                 expected_features: Optional[int] = None):
        self.drift_sigma = float(drift_sigma)
        self.range_factor = float(range_factor)
        self.nonfinite_check = bool(nonfinite_check)
        self.expected_features = expected_features
        # running reference over accepted batches (Welford on labels)
        self._n = 0
        self._label_mean = 0.0
        self._label_m2 = 0.0
        self._feat_min: Optional[np.ndarray] = None
        self._feat_max: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    def check(self, batch) -> List[str]:
        """Problems with one batch (empty = accept)."""
        errs: List[str] = []
        if _faults.fire("ingest.validate") == "reject":
            errs.append("injected fault (ingest.validate:reject)")
        X = np.asarray(batch.X)
        y = np.asarray(batch.y)
        if X.ndim != 2 or X.shape[0] == 0 or X.shape[1] == 0:
            errs.append(f"X must be a non-empty 2-D matrix, got shape "
                        f"{X.shape}")
            return errs               # everything below needs rows
        if not (np.issubdtype(X.dtype, np.floating) or
                np.issubdtype(X.dtype, np.integer)):
            errs.append(f"X dtype {X.dtype} is not numeric")
            return errs
        if y.ndim != 1 or y.shape[0] != X.shape[0]:
            errs.append(f"y shape {y.shape} does not match "
                        f"{X.shape[0]} rows")
            return errs
        if not (np.issubdtype(y.dtype, np.floating) or
                np.issubdtype(y.dtype, np.integer)):
            errs.append(f"y dtype {y.dtype} is not numeric")
            return errs
        w = getattr(batch, "weight", None)
        if w is not None:
            w = np.asarray(w)
            if w.ravel().shape[0] != X.shape[0]:
                errs.append(f"weight length {w.ravel().shape[0]} != "
                            f"{X.shape[0]} rows")
        g = getattr(batch, "group", None)
        if g is not None:
            g = np.asarray(g).ravel()
            if not np.issubdtype(g.dtype, np.integer) and \
                    not np.all(g == np.floor(g)):
                errs.append("group contains non-integer counts")
            elif int(g.sum()) != X.shape[0]:
                errs.append(f"group counts sum to {int(g.sum())}, "
                            f"batch has {X.shape[0]} rows")
        n_feat = X.shape[1]
        ref_feat = self._feat_min.shape[0] \
            if self._feat_min is not None else self.expected_features
        if ref_feat is not None and n_feat != int(ref_feat):
            errs.append(f"feature width {n_feat} != reference "
                        f"{int(ref_feat)}")
            return errs
        if self.nonfinite_check:
            # scan in the NATIVE dtype: integer arrays are always
            # finite, and isfinite on float32 avoids materializing a
            # float64 copy of an mmap shard just to look at it
            bad_x = 0 if np.issubdtype(X.dtype, np.integer) else \
                int((~np.isfinite(X)).sum())
            bad_y = 0 if np.issubdtype(y.dtype, np.integer) else \
                int((~np.isfinite(y)).sum())
            if bad_x or bad_y:
                errs.append(f"non-finite values: {bad_x} in X, "
                            f"{bad_y} in labels")
                return errs           # drift stats on NaN are noise
        if self._n > 0:
            errs.extend(self._check_drift(X, y))
        return errs

    def _check_drift(self, X: np.ndarray, y: np.ndarray) -> List[str]:
        errs: List[str] = []
        if self.drift_sigma > 0 and self._n > 1:
            ref_std = float(np.sqrt(self._label_m2 / (self._n - 1)))
            # a degenerate (constant-label) reference can't scale a
            # z-test; fall back to the label magnitude as the unit
            scale = max(ref_std, 1e-3 * max(abs(self._label_mean), 1.0))
            mean = float(np.mean(y, dtype=np.float64))
            z = abs(mean - self._label_mean) / scale
            if z > self.drift_sigma:
                errs.append(
                    f"label drift: batch mean {mean:.4g} is "
                    f"{z:.1f} sigma from the reference mean "
                    f"{self._label_mean:.4g} (bound "
                    f"{self.drift_sigma:g})")
        if self.range_factor > 0 and self._feat_min is not None:
            span = np.maximum(self._feat_max - self._feat_min, 1e-12)
            lo = self._feat_min - self.range_factor * span
            hi = self._feat_max + self.range_factor * span
            # comparisons against the f64 bounds upcast per ufunc
            # buffer — no full float64 copy of the batch
            viol = (X < lo) | (X > hi)
            if self.nonfinite_check is False and \
                    not np.issubdtype(X.dtype, np.integer):
                viol &= np.isfinite(X)
            n_viol = int(viol.sum())
            if n_viol:
                worst = int(np.argmax(viol.sum(axis=0)))
                errs.append(
                    f"feature range drift: {n_viol} value(s) outside "
                    f"the reference range x{self.range_factor:g} "
                    f"(worst feature {worst})")
        return errs

    # ------------------------------------------------------------------
    def observe(self, batch) -> None:
        """Fold an ACCEPTED batch into the running reference.
        Reductions run in the batch's native dtype with float64
        ACCUMULATORS — no float64 copy of a (possibly mmap) shard."""
        X = np.asarray(batch.X)
        y = np.asarray(batch.y).ravel()
        # chunk-merged Welford (Chan et al.): exact pooled mean/M2
        # without keeping per-row history
        n_new = y.shape[0]
        mean_new = float(np.mean(y, dtype=np.float64))
        var_new = float(np.var(y, dtype=np.float64)) * n_new
        if self._n == 0:
            self._label_mean = mean_new
            self._label_m2 = var_new
        else:
            delta = mean_new - self._label_mean
            tot = self._n + n_new
            self._label_mean += delta * n_new / tot
            self._label_m2 += var_new + \
                delta * delta * self._n * n_new / tot
        self._n += n_new
        fmin = np.min(X, axis=0).astype(np.float64)
        fmax = np.max(X, axis=0).astype(np.float64)
        if self._feat_min is None:
            self._feat_min, self._feat_max = fmin, fmax
        else:
            self._feat_min = np.minimum(self._feat_min, fmin)
            self._feat_max = np.maximum(self._feat_max, fmax)

    # ------------------------------------------------------------------
    def state(self) -> Dict[str, Any]:
        """JSON-able reference state (the daemon ledger carries it)."""
        return {
            "n": int(self._n),
            "label_mean": float(self._label_mean),
            "label_m2": float(self._label_m2),
            "feat_min": None if self._feat_min is None else
            [float(v) for v in self._feat_min],
            "feat_max": None if self._feat_max is None else
            [float(v) for v in self._feat_max],
        }

    def restore_state(self, state: Optional[Dict[str, Any]]) -> None:
        if not state:
            return
        self._n = int(state.get("n", 0))
        self._label_mean = float(state.get("label_mean", 0.0))
        self._label_m2 = float(state.get("label_m2", 0.0))
        fmin = state.get("feat_min")
        fmax = state.get("feat_max")
        self._feat_min = None if fmin is None else \
            np.asarray(fmin, np.float64)
        self._feat_max = None if fmax is None else \
            np.asarray(fmax, np.float64)
