"""Typed continual-training configuration.

Canonical parameter definitions (names, defaults, aliases, docs) live
in the single-source registry — ``lightgbm_tpu/config.py``, group
``continual`` — so ``docs/Parameters.md`` and CLI alias resolution
cover them like every other knob.  This dataclass is the resolved
subset the daemon passes around; build it with
:meth:`ContinualConfig.from_params` from a raw params dict, a resolved
:class:`~lightgbm_tpu.config.Config`, or nothing (defaults).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Union


@dataclasses.dataclass
class ContinualConfig:
    # batch source: directory of npz shards / mmap .npy pairs,
    # consumed in name order
    ingest_dir: str = ""
    quarantine_dir: str = ""      # '' -> <ingest_dir>/_quarantine
    processed_dir: str = ""       # '' -> <ingest_dir>/_processed
    # per-batch training
    rounds_per_batch: int = 10
    refit_every: int = 0          # every Nth batch refits; 0 = never
    # loop pacing / termination
    poll_s: float = 1.0
    idle_exit_s: float = 0.0      # 0 = run until preempted
    max_batches: int = 0          # 0 = unbounded
    # robustness
    stall_timeout_s: float = 120.0
    max_batch_retries: int = 2
    read_retries: int = 3
    backoff_base_s: float = 0.1
    backoff_max_s: float = 5.0
    # validation gates
    drift_sigma: float = 8.0      # 0 disables the label-drift gate
    range_factor: float = 10.0    # 0 disables the feature-range gate
    nonfinite_check: bool = True
    # in-batch periodic checkpoint cadence; 0 = batch boundaries only
    snapshot_freq: int = 0

    @classmethod
    def from_params(cls, params: Union[None, Dict[str, Any], Any] = None
                    ) -> "ContinualConfig":
        from ..config import Config
        if params is None:
            cfg = Config()
        elif isinstance(params, Config):
            cfg = params
        else:
            cfg = Config(dict(params))
        return cls(
            ingest_dir=str(cfg.continual_ingest_dir or ""),
            quarantine_dir=str(cfg.continual_quarantine_dir or ""),
            processed_dir=str(cfg.continual_processed_dir or ""),
            rounds_per_batch=int(cfg.continual_rounds_per_batch),
            refit_every=int(cfg.continual_refit_every),
            poll_s=float(cfg.continual_poll_s),
            idle_exit_s=float(cfg.continual_idle_exit_s),
            max_batches=int(cfg.continual_max_batches),
            stall_timeout_s=float(cfg.continual_stall_timeout_s),
            max_batch_retries=int(cfg.continual_max_batch_retries),
            read_retries=int(cfg.continual_read_retries),
            backoff_base_s=float(cfg.continual_backoff_base_s),
            backoff_max_s=float(cfg.continual_backoff_max_s),
            drift_sigma=float(cfg.continual_drift_sigma),
            range_factor=float(cfg.continual_range_factor),
            nonfinite_check=bool(cfg.continual_nonfinite_check),
            snapshot_freq=int(cfg.continual_snapshot_freq))

    def resolved_quarantine_dir(self) -> str:
        return self.quarantine_dir or \
            os.path.join(self.ingest_dir, "_quarantine")

    def resolved_processed_dir(self) -> str:
        return self.processed_dir or \
            os.path.join(self.ingest_dir, "_processed")

    def validate(self) -> None:
        if not self.ingest_dir:
            raise ValueError("continual_ingest_dir must be set")
        if self.rounds_per_batch < 1:
            raise ValueError("continual_rounds_per_batch must be >= 1")
        if self.poll_s <= 0:
            raise ValueError("continual_poll_s must be > 0")
        if self.backoff_max_s < self.backoff_base_s:
            raise ValueError("continual backoff must satisfy "
                             "base <= max")
        for name in ("idle_exit_s", "max_batches", "stall_timeout_s",
                     "max_batch_retries", "read_retries",
                     "backoff_base_s", "drift_sigma", "range_factor",
                     "refit_every", "snapshot_freq"):
            if getattr(self, name) < 0:
                raise ValueError(f"continual_{name} must be >= 0")
