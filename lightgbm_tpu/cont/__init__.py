"""Continual training daemon (``task=continual``): a preemption-safe,
self-healing ingest -> validate -> train -> checkpoint -> publish loop.

See ``docs/Continual.md`` for the architecture; the pieces:

- :class:`~.source.DirectoryBatchSource` — tails a directory of
  npz/mmap batch shards with bounded-backoff retries and quarantine.
- :class:`~.validate.BatchValidator` — schema/dtype/shape, non-finite
  scan, label-distribution and feature-range drift gates.
- :class:`~.trainer.ContinualTrainer` — the daemon: warm-start extend
  or leaf refit per batch, PR 5 checkpoints, stall watchdog,
  numerical-health rewind, preemption drain.
"""
from .config import ContinualConfig
from .source import Batch, BatchSource, DirectoryBatchSource
from .trainer import ContinualTrainer
from .validate import BatchValidator

__all__ = ["Batch", "BatchSource", "BatchValidator", "ContinualConfig",
           "ContinualTrainer", "DirectoryBatchSource"]
