"""Batch sources for the continual training daemon.

The out-of-core framing ("Out-of-Core GPU Gradient Boosting",
PAPERS.md): training data arrives as a stream of finite batch shards
on disk, not a resident matrix.  :class:`DirectoryBatchSource` tails a
directory in NAME order — producers write shards under temporary names
and rename into place, so a sorted listing is a stable consumption
order — and owns the failure taxonomy of getting bytes off disk:

- **transient** read failures (``OSError``: flaky NFS, a mid-copy
  file) retry under bounded exponential backoff
  (``continual_read_retries`` x ``continual_backoff_base_s``), each
  retry emitting a ``continual``/``backoff`` telemetry record;
- **non-transient** failures (truncated zip, missing arrays, a pickle
  where an array should be) quarantine the file immediately — retrying
  a deterministic parse error just burns the backoff budget.

Quarantined batches are MOVED (``os.replace``) into the quarantine
directory so the ingest dir never wedges on one bad file, and every
move emits a ``continual``/``quarantine`` record carrying the reason —
the accounting the chaos e2e reconciles.

Shard formats:

- ``<name>.npz`` with arrays ``X`` and ``y`` (or ``label``), optional
  ``weight`` and ``group``;
- mmap pairs ``<name>.X.npy`` + ``<name>.y.npy`` (optional
  ``<name>.weight.npy`` / ``<name>.group.npy``), loaded with
  ``mmap_mode='r'`` — the zero-copy form for shards written by a
  separate producer process.

Fault-injection point: ``ingest.read`` (modes ``error`` = transient,
``corrupt`` = non-transient; ``utils/faults.py``).
"""
from __future__ import annotations

import dataclasses
import glob
import os
import time
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import faults as _faults
from ..utils.log import Log

__all__ = ["Batch", "BatchSource", "DirectoryBatchSource"]


@dataclasses.dataclass
class Batch:
    """One ingested training batch."""

    name: str
    paths: Tuple[str, ...]
    X: np.ndarray
    y: np.ndarray
    weight: Optional[np.ndarray] = None
    group: Optional[np.ndarray] = None

    @property
    def rows(self) -> int:
        return int(np.asarray(self.X).shape[0]) if \
            np.asarray(self.X).ndim >= 1 else 0


class BatchSource:
    """Abstract batch source: ``next_batch`` yields the next pending
    batch (or None), ``quarantine``/``mark_done`` retire it.
    ``quarantined`` counts every quarantine THIS source performed —
    reads before validation and trainer-initiated rejects alike — so
    the daemon's accounting has one source of truth."""

    quarantined: int = 0

    def pending(self) -> List[str]:
        raise NotImplementedError

    def next_batch(self) -> Optional[Batch]:
        raise NotImplementedError

    def quarantine(self, batch, reason: str, detail: str = "") -> None:
        raise NotImplementedError

    def mark_done(self, batch: Batch) -> None:
        raise NotImplementedError


class DirectoryBatchSource(BatchSource):
    """Tail a directory of npz / mmap-npy batch shards in name order."""

    def __init__(self, root: str, quarantine_dir: str = "",
                 processed_dir: str = "", read_retries: int = 3,
                 backoff_base_s: float = 0.1, backoff_max_s: float = 5.0,
                 recorder=None):
        self.root = str(root)
        self.quarantine_dir = quarantine_dir or \
            os.path.join(self.root, "_quarantine")
        self.processed_dir = processed_dir or \
            os.path.join(self.root, "_processed")
        self.read_retries = max(int(read_retries), 0)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.recorder = recorder
        os.makedirs(self.root, exist_ok=True)

    # -- telemetry -----------------------------------------------------
    def _emit(self, event: str, **fields) -> None:
        from ..utils import telemetry as _telemetry
        _telemetry.counters.incr(f"continual_{event}s")
        rec = self.recorder or _telemetry.get_recorder()
        if rec is not None:
            rec.emit("continual", event=event, **fields)

    # -- discovery -----------------------------------------------------
    def pending(self) -> List[str]:
        """Batch names awaiting consumption, sorted (= consumption
        order).  Hidden/underscore names and in-flight temp files are
        producers' business, not batches."""
        names = set()
        for path in glob.glob(os.path.join(self.root, "*.npz")):
            base = os.path.basename(path)
            if not base.startswith((".", "_")):
                names.add(base)
        for path in glob.glob(os.path.join(self.root, "*.X.npy")):
            base = os.path.basename(path)
            if base.startswith((".", "_")):
                continue
            stem = base[:-len(".X.npy")]
            # a pair is pending only once BOTH halves landed — a
            # producer renaming X before y must not get the batch
            # quarantined (and its late y orphaned) by the gap
            if os.path.exists(os.path.join(self.root,
                                           f"{stem}.y.npy")):
                names.add(stem)
        return sorted(names)

    def _paths_for(self, name: str) -> Tuple[str, ...]:
        if name.endswith(".npz"):
            return (os.path.join(self.root, name),)
        out = [os.path.join(self.root, f"{name}.X.npy"),
               os.path.join(self.root, f"{name}.y.npy")]
        for part in ("weight", "group"):
            p = os.path.join(self.root, f"{name}.{part}.npy")
            if os.path.exists(p):
                out.append(p)
        return tuple(out)

    # -- reading -------------------------------------------------------
    @staticmethod
    def _arrays_from_npz(path: str) -> Dict[str, Any]:
        with np.load(path, allow_pickle=False) as z:
            files = set(z.files)
            if "X" not in files and "x" not in files:
                raise ValueError("npz batch has no 'X' array")
            X = z["X"] if "X" in files else z["x"]
            y = None
            for key in ("y", "label", "labels"):
                if key in files:
                    y = z[key]
                    break
            if y is None:
                raise ValueError("npz batch has no 'y'/'label' array")
            out = {"X": X, "y": y}
            if "weight" in files:
                out["weight"] = z["weight"]
            if "group" in files:
                out["group"] = z["group"]
        return out

    def _load(self, name: str) -> Batch:
        mode = _faults.fire("ingest.read")
        if mode == "error":
            raise OSError(f"injected fault (ingest.read:error) "
                          f"reading {name}")
        if mode == "corrupt":
            raise ValueError(f"injected fault (ingest.read:corrupt) "
                             f"parsing {name}")
        paths = self._paths_for(name)
        if name.endswith(".npz"):
            arrays = self._arrays_from_npz(paths[0])
        else:
            # mmap pair: X/y stay memory-mapped (read-only views);
            # Dataset construction copies what it bins
            arrays = {"X": np.load(paths[0], mmap_mode="r",
                                   allow_pickle=False),
                      "y": np.load(paths[1], mmap_mode="r",
                                   allow_pickle=False)}
            for part in ("weight", "group"):
                p = os.path.join(self.root, f"{name}.{part}.npy")
                if os.path.exists(p):
                    arrays[part] = np.load(p, mmap_mode="r",
                                           allow_pickle=False)
        return Batch(name=name, paths=paths, X=arrays["X"],
                     y=arrays["y"], weight=arrays.get("weight"),
                     group=arrays.get("group"))

    def next_batch(self) -> Optional[Batch]:
        """Load the next pending batch.  Transient read failures back
        off and retry; exhausted retries and parse failures quarantine
        the file and move on to the NEXT poll (returning None so the
        caller re-enters its loop checks)."""
        pending = self.pending()
        if not pending:
            return None
        name = pending[0]
        attempt = 0
        while True:
            try:
                return self._load(name)
            except OSError as exc:
                attempt += 1
                if attempt > self.read_retries:
                    self.quarantine(name, "read",
                                    f"transient read failure persisted "
                                    f"through {attempt} attempts: {exc}")
                    return None
                sleep_s = min(self.backoff_base_s * (2 ** (attempt - 1)),
                              self.backoff_max_s)
                Log.warning("continual: transient read failure on %s "
                            "(attempt %d/%d, backing off %.2fs): %s",
                            name, attempt, self.read_retries, sleep_s,
                            exc)
                self._emit("backoff", batch=name, attempt=attempt,
                           sleep_s=round(sleep_s, 3),
                           error=str(exc)[:200])
                time.sleep(sleep_s)
            except (ValueError, KeyError, zipfile.BadZipFile,
                    EOFError) as exc:
                # deterministic parse failure: retrying cannot help
                self.quarantine(name, "read", f"unreadable batch: {exc}")
                return None

    # -- retirement ----------------------------------------------------
    def _move_all(self, name: str, dest_dir: str) -> None:
        os.makedirs(dest_dir, exist_ok=True)
        for path in self._paths_for(name):
            if os.path.exists(path):
                os.replace(path,
                           os.path.join(dest_dir,
                                        os.path.basename(path)))

    def quarantine(self, batch, reason: str, detail: str = "") -> None:
        """Move a rejected batch (or raw name) out of the ingest dir
        and account for it in telemetry — the ingest stream must never
        wedge on one bad file."""
        name = batch if isinstance(batch, str) else batch.name
        self.quarantined += 1
        try:
            self._move_all(name, self.quarantine_dir)
        except OSError as exc:  # pragma: no cover - quarantine FS issue
            Log.warning("continual: could not quarantine %s: %s",
                        name, exc)
        Log.warning("continual: QUARANTINED batch %s (%s)%s", name,
                    reason, f": {detail}" if detail else "")
        self._emit("quarantine", batch=name, reason=str(reason),
                   error=str(detail)[:300])

    def mark_done(self, batch: Batch) -> None:
        self._move_all(batch.name, self.processed_dir)
