"""Training callbacks (reference ``python-package/lightgbm/callback.py``):
``print_evaluation``, ``record_evaluation``, ``reset_parameter``,
``early_stopping`` over the same CallbackEnv protocol."""
from __future__ import annotations

import collections
from typing import Callable, Dict, List

from .utils.log import Log

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:  # cv: (name, metric, mean, higher_better, stdv)
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError(f"Wrong metric value {value}")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and \
                (env.iteration + 1) % period == 0:
            result = "\t".join(_format_eval_result(x, show_stdv)
                               for x in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result must be a dict")
    eval_result.clear()

    def _callback(env: CallbackEnv) -> None:
        for item in env.evaluation_result_list:
            name, metric, value = item[0], item[1], item[2]
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, [])
            eval_result[name][metric].append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Per-iteration parameter schedules (list or callable per param);
    currently supports ``learning_rate``."""
    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"length of list {key} has to be equal "
                                     "to 'num_boost_round'")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
        if new_params:
            if "learning_rate" in new_params:
                env.model._gbdt.shrinkage_rate = \
                    float(new_params["learning_rate"])
            env.params.update(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List = []
    cmp_op: List[Callable] = []
    enabled = [True]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            Log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and "
                             "eval metric is required for evaluation")
        if verbose:
            Log.info("Training until validation scores don't improve for "
                     "%d rounds.", stopping_rounds)
        for item in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if item[3]:  # higher better
                best_score.append(float("-inf"))
                cmp_op.append(lambda a, b: a > b)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda a, b: a < b)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, item in enumerate(env.evaluation_result_list):
            score = item[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            # train metric does not trigger early stopping
            if item[0] == "training":
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    Log.info("Early stopping, best iteration is:\n[%d]\t%s",
                             best_iter[i] + 1,
                             "\t".join(_format_eval_result(x)
                                       for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    Log.info("Did not meet early stopping. Best iteration "
                             "is:\n[%d]\t%s", best_iter[i] + 1,
                             "\t".join(_format_eval_result(x)
                                       for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if first_metric_only:
                break
    _callback.order = 30
    return _callback
