"""Training callbacks.

Capability parity with ``python-package/lightgbm/callback.py`` —
periodic metric printing, metric recording, per-iteration parameter
schedules, and validation-based early stopping — implemented as small
callback classes over a shared :class:`CallbackEnv` snapshot.  The env
tuple and the ``order`` / ``before_iteration`` attributes are the
protocol the training loop (``engine.train``) sorts and dispatches on.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from .utils.log import Log


@dataclasses.dataclass(frozen=True)
class CallbackEnv:
    """Per-iteration snapshot handed to every callback."""
    model: Any
    params: Dict[str, Any]
    iteration: int
    begin_iteration: int
    end_iteration: int
    evaluation_result_list: Optional[List[Tuple]]

    # tuple-style access kept for callbacks written against the
    # namedtuple form of the protocol (plain references, no copying)
    def __getitem__(self, i):
        return (self.model, self.params, self.iteration,
                self.begin_iteration, self.end_iteration,
                self.evaluation_result_list)[i]


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _format_eval_result(entry, show_stdv: bool = True) -> str:
    """Render one eval tuple: (data, metric, value, higher_better[, stdv])."""
    data, metric, value = entry[0], entry[1], entry[2]
    if len(entry) == 5 and show_stdv:
        return f"{data}'s {metric}: {value:g} + {entry[4]:g}"
    if len(entry) in (4, 5):
        return f"{data}'s {metric}: {value:g}"
    raise ValueError(f"Wrong metric value {entry}")


class _PrintEvaluation:
    order = 10
    before_iteration = False

    def __init__(self, period: int, show_stdv: bool):
        self.period = period
        self.show_stdv = show_stdv

    def __call__(self, env: CallbackEnv) -> None:
        if self.period <= 0 or not env.evaluation_result_list:
            return
        if (env.iteration + 1) % self.period == 0:
            Log.info("[%d]\t%s", env.iteration + 1,
                     "\t".join(_format_eval_result(e, self.show_stdv)
                               for e in env.evaluation_result_list))


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    return _PrintEvaluation(period, show_stdv)


class _RecordEvaluation:
    order = 20
    before_iteration = False

    def __init__(self, eval_result: Dict):
        if not isinstance(eval_result, dict):
            raise TypeError("eval_result must be a dict")
        eval_result.clear()
        self.store = eval_result

    def __call__(self, env: CallbackEnv) -> None:
        for entry in env.evaluation_result_list or []:
            data, metric, value = entry[0], entry[1], entry[2]
            self.store.setdefault(data, {}).setdefault(metric, []).append(
                value)


def record_evaluation(eval_result: Dict) -> Callable:
    return _RecordEvaluation(eval_result)


class _RecordTelemetry:
    """Attach a run recorder (``utils/telemetry.py``) to the booster
    before the first iteration — the callback form of the
    ``telemetry_file`` config parameter, for callers who want to hand
    in an existing :class:`RunRecorder` (the bench) or an in-memory
    recorder (tests).  Iteration/predict records are emitted by the
    booster itself once a recorder is attached; eval records by the
    training loop."""
    order = 5
    before_iteration = True

    def __init__(self, target):
        self.target = target
        self.recorder = None

    def __call__(self, env: CallbackEnv) -> None:
        if self.recorder is not None:
            return
        gbdt = getattr(env.model, "_gbdt", None)
        if gbdt is None:   # cv hands a CVBooster; attach per fold
            for bst in getattr(env.model, "boosters", []):
                bst._gbdt.attach_telemetry(self.target)
            self.recorder = True
            return
        self.recorder = gbdt.attach_telemetry(self.target)


def record_telemetry(target) -> Callable:
    """Feed structured run telemetry to ``target`` — a JSONL path or a
    :class:`lightgbm_tpu.utils.telemetry.RunRecorder`.  Equivalent to
    setting ``telemetry_file=<path>`` in the params."""
    return _RecordTelemetry(target)


class _ResetParameter:
    order = 10
    before_iteration = True

    def __init__(self, schedules: Dict[str, Any]):
        self.schedules = schedules

    def __call__(self, env: CallbackEnv) -> None:
        updates = {}
        for key, sched in self.schedules.items():
            if callable(sched):
                updates[key] = sched(env.iteration - env.begin_iteration)
            else:
                if not isinstance(sched, (list, tuple)):
                    raise ValueError(
                        f"reset_parameter: {key!r} must be a list of "
                        f"per-iteration values or a callable "
                        f"iteration -> value, got {type(sched).__name__}")
                values = list(sched)
                if len(values) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"length of list {key!r} must equal num_boost_round")
                updates[key] = values[env.iteration - env.begin_iteration]
        if "learning_rate" in updates:
            lr = float(updates["learning_rate"])
            env.model._gbdt.shrinkage_rate = lr
            # modes that derive their per-iteration shrinkage from the
            # configured rate (DART's k/(k+1) scaling) read the config,
            # matching the reference's ResetConfig path
            env.model._gbdt.config.learning_rate = lr
        env.params.update(updates)


def reset_parameter(**kwargs) -> Callable:
    """Per-iteration parameter schedules: each kwarg is a list (one value
    per round) or a callable iteration -> value.  ``learning_rate`` is
    applied to the booster's shrinkage."""
    return _ResetParameter(kwargs)


@dataclasses.dataclass
class _MetricState:
    """Best-so-far tracker for one (dataset, metric) eval stream."""
    higher_better: bool
    best_value: float = None
    best_round: int = 0
    best_snapshot: Optional[List[Tuple]] = None

    def improved(self, value: float) -> bool:
        if self.best_value is None:
            return True
        return value > self.best_value if self.higher_better \
            else value < self.best_value


class _EarlyStopping:
    order = 30
    before_iteration = False

    def __init__(self, patience: int, first_metric_only: bool, verbose: bool):
        self.patience = patience
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.states: Optional[List[_MetricState]] = None
        self.active = True

    def _start(self, env: CallbackEnv) -> None:
        # DART reweights past trees every iteration, so "best iteration"
        # is not well-defined and early stopping is disabled
        boosting = next((env.params[a] for a in
                         ("boosting", "boosting_type", "boost")
                         if a in env.params), "gbdt")
        if boosting == "dart":
            self.active = False
            Log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and "
                             "eval metric is required for evaluation")
        if self.verbose:
            Log.info("Training until validation scores don't improve for "
                     "%d rounds.", self.patience)
        self.states = [_MetricState(higher_better=bool(entry[3]))
                       for entry in env.evaluation_result_list]

    def _finish(self, state: _MetricState, reason: str) -> None:
        if self.verbose:
            Log.info("%s, best iteration is:\n[%d]\t%s", reason,
                     state.best_round + 1,
                     "\t".join(_format_eval_result(e)
                               for e in state.best_snapshot))
        raise EarlyStopException(state.best_round, state.best_snapshot)

    def __call__(self, env: CallbackEnv) -> None:
        if self.states is None and self.active:
            self._start(env)
        if not self.active:
            return
        for state, entry in zip(self.states, env.evaluation_result_list):
            if state.improved(entry[2]):
                state.best_value = entry[2]
                state.best_round = env.iteration
                state.best_snapshot = env.evaluation_result_list
            if entry[0] == "training":
                continue  # train metric never stops training
            if env.iteration - state.best_round >= self.patience:
                self._finish(state, "Early stopping")
            if env.iteration == env.end_iteration - 1:
                self._finish(state, "Did not meet early stopping. Best "
                                    "iteration")
            if self.first_metric_only:
                break


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """Stop when no validation metric improves for ``stopping_rounds``
    consecutive rounds (training metrics are tracked but never trigger)."""
    return _EarlyStopping(stopping_rounds, first_metric_only, verbose)
