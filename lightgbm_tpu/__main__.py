"""CLI application: ``python -m lightgbm_tpu config=train.conf [k=v ...]``.

Capability parity with the reference CLI (``src/application/
application.cpp:30``, ``src/main.cpp``): ``key=value`` args merged over
an optional config file, dispatch on ``task`` = train / predict /
convert_model / refit, reading the reference's ``.conf`` format
verbatim (the ``examples/*/train.conf`` files run unmodified); plus
``task=serve`` — the online micro-batching endpoint the reference has
no analog of (``lightgbm_tpu/serve/``).
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List

import numpy as np

from .config import Config
from .utils.log import Log


def _parse_args(argv: List[str]) -> Dict[str, str]:
    """CLI ``key=value`` pairs + optional ``config=`` file
    (``Application::LoadParameters``, ``application.cpp:48``): explicit
    CLI keys win over config-file keys."""
    cli: Dict[str, str] = {}
    for a in argv:
        if "=" not in a:
            Log.fatal("unknown argument %r (expected key=value)", a)
        k, v = a.split("=", 1)
        cli[k.strip()] = v.strip()
    conf_path = cli.get("config", cli.get("config_file", ""))
    params: Dict[str, str] = {}
    if conf_path:
        with open(conf_path) as f:
            params.update(Config.str2dict(f.read()))
        # data paths inside a conf file are relative to the conf's dir
        base = os.path.dirname(os.path.abspath(conf_path))
        for key in ("data", "train", "train_data", "train_data_file",
                    "valid", "test", "valid_data", "valid_data_file",
                    "test_data", "input_model", "output_model",
                    "output_result", "machine_list_filename",
                    "machine_list_file", "machine_list", "mlist",
                    "forcedsplits_filename", "forced_splits_filename",
                    "forced_splits_file", "forced_splits"):
            if key in params and params[key]:
                p = params[key]
                vals = []
                for item in p.split(","):
                    item = item.strip()
                    if item and not os.path.isabs(item) and \
                            not os.path.exists(item):
                        cand = os.path.join(base, item)
                        if os.path.exists(cand):
                            item = cand
                    vals.append(item)
                params[key] = ",".join(vals)
    params.update(cli)
    params.pop("config", None)
    params.pop("config_file", None)
    return params


def _task_train(params: Dict[str, str], config: Config) -> None:
    from .basic import Booster, Dataset
    from .engine import train

    if not config.data:
        Log.fatal("No training data: set data=<file>")
    train_set = Dataset(config.data, params=params)
    if config.save_binary:
        # cache the binned dataset next to the text file
        # (Dataset::SaveBinaryFile; reloaded transparently by
        # data=<file>.bin on later runs); skip when the input already
        # IS a binary cache
        from .io.dataset import TpuDataset
        if not TpuDataset.is_binary_file(config.data):
            train_set.save_binary(config.data + ".bin")
    valid_sets, valid_names = [], []
    if config.valid:
        # valid_data_initscores: one init-score file per valid set
        vinits = [p.strip() for p in
                  str(config.valid_data_initscores or "").split(",")]
        for i, path in enumerate(str(config.valid).split(",")):
            path = path.strip()
            if not path:
                continue
            init = None
            if i < len(vinits) and vinits[i]:
                from .io.parser import load_float_file
                init = load_float_file(vinits[i])
            valid_sets.append(Dataset(path, params=params,
                                      init_score=init,
                                      reference=train_set))
            valid_names.append(os.path.basename(path))

    callbacks = []
    if config.snapshot_freq > 0 and not config.checkpoint_dir:
        # reference save_period behavior: model-text snapshots.  With
        # checkpoint_dir set, snapshot_freq instead drives the full
        # resumable checkpoints inside engine.train (ckpt/manager.py)
        freq, out_path = config.snapshot_freq, config.output_model

        def _snapshot(env):
            i = env.iteration + 1
            if i % freq == 0:
                env.model.save_model(f"{out_path}.snapshot_iter_{i}")
                Log.info("Saved snapshot at iteration %d", i)
        callbacks.append(_snapshot)

    init_model = config.input_model or None
    booster = train(params, train_set,
                    num_boost_round=config.num_iterations,
                    valid_sets=valid_sets or None,
                    valid_names=valid_names or None,
                    init_model=init_model,
                    callbacks=callbacks or None,
                    verbose_eval=max(config.metric_freq, 1))
    booster.save_model(config.output_model)
    Log.info("Finished training; model saved to %s", config.output_model)
    _close_telemetry(booster)


def _task_predict(params: Dict[str, str], config: Config) -> None:
    from .basic import Booster
    from .io.parser import parse_file

    if not config.input_model:
        Log.fatal("No model file: set input_model=<file>")
    if not config.data:
        Log.fatal("No data to predict: set data=<file>")
    from .io.parser import parse_file_full
    booster = Booster(model_file=config.input_model)
    if config.telemetry_file:
        # loaded boosters skip GBDT.__init__; the inference entry
        # points still feed run records once a recorder is attached
        booster._gbdt.attach_telemetry(config.telemetry_file)
    # drop the same non-feature columns training dropped, or feature
    # indices shift against the trained model
    X, _, _, _, _ = parse_file_full(
        config.data, header=config.header,
        label_column=config.label_column,
        ignore_columns=config.ignore_column,
        weight_column=config.weight_column,
        group_column=config.group_column)
    num_iteration = config.num_iteration_predict \
        if config.num_iteration_predict > 0 else None
    kw = {}
    if config.pred_early_stop:
        kw = {"pred_early_stop": True,
              "pred_early_stop_freq": config.pred_early_stop_freq,
              "pred_early_stop_margin": config.pred_early_stop_margin}
    if config.predict_leaf_index:
        out = booster.predict(X, num_iteration=num_iteration,
                              pred_leaf=True)
    elif config.predict_contrib:
        out = booster.predict(X, num_iteration=num_iteration,
                              pred_contrib=True)
    elif config.predict_raw_score:
        out = booster.predict(X, num_iteration=num_iteration,
                              raw_score=True, **kw)
    else:
        out = booster.predict(X, num_iteration=num_iteration, **kw)
    out = np.atleast_1d(np.asarray(out))
    with open(config.output_result, "w") as f:
        if out.ndim == 1:
            f.writelines(f"{v:.18g}\n" for v in out)
        else:
            f.writelines("\t".join(f"{v:.18g}" for v in row) + "\n"
                         for row in out)
    Log.info("Finished prediction; results saved to %s",
             config.output_result)
    _close_telemetry(booster)


def _close_telemetry(booster) -> None:
    """Flush the run_end record + Log summary at task end (the atexit
    hook would also fire, but an explicit close keeps the CLI's JSONL
    complete even when the interpreter is torn down abruptly)."""
    rec = getattr(booster._gbdt, "_telemetry", None)
    if rec is not None:
        rec.close()


def _task_convert_model(params: Dict[str, str], config: Config) -> None:
    from .basic import Booster
    from .models.codegen import model_to_ifelse

    if not config.input_model:
        Log.fatal("No model file: set input_model=<file>")
    if config.convert_model_language not in ("", "cpp"):
        Log.fatal("convert_model_language %r not supported (cpp only)",
                  config.convert_model_language)
    booster = Booster(model_file=config.input_model)
    code = model_to_ifelse(booster._gbdt.models,
                           booster._gbdt.num_tree_per_iteration,
                           booster._objective_string())
    with open(config.convert_model, "w") as f:
        f.write(code)
    Log.info("Finished converting model; code saved to %s",
             config.convert_model)


def _task_serve(params: Dict[str, str], config: Config) -> None:
    """Online serving: load the model, publish it to the registry
    (flatten + pre-warm), serve the threaded JSON endpoint until a
    SIGTERM/SIGINT triggers the graceful drain (``serve/http.py``).
    Pointed at a checkpoint ROOT, a watcher thread additionally
    tracks the root: each new snapshot is manifest-verified and
    canary-scored before auto-publish, with telemetry-driven rollback
    (``serve/watcher.py``, ``docs/Resilience.md``)."""
    from .basic import Booster
    from .ckpt import CheckpointManager
    from .obs import flight as _flight
    from .obs import spans as _spans
    from .serve import (CheckpointWatcher, FleetConfig, RegistryTarget,
                        Server, ServeConfig)
    from .serve.http import serve_http

    if not config.input_model:
        Log.fatal("No model file: set input_model=<file> (a model "
                  "file, a ckpt_* checkpoint directory, or a "
                  "checkpoint root)")
    _flight.ensure_installed(config)
    server = Server(config=ServeConfig.from_params(config))
    # a supervisor-spawned replica marks its boot against the spawn
    # trace (LTPU_TRACE env carrier) without adopting it process-wide
    boot_carrier = _spans.parse(os.environ.get(_spans.ENV_VAR, ""))
    if boot_carrier is not None:
        _spans.point("replica_boot", boot_carrier,
                     recorder=server._recorder, pid=os.getpid())
    watcher = None
    if os.path.isdir(config.input_model):
        # serve straight from a training checkpoint directory/root:
        # manifest-validated, newest-valid-wins (ckpt/manager.py)
        server.registry.publish_from_checkpoint(config.input_model)
        if not CheckpointManager.is_checkpoint_dir(config.input_model):
            # a ROOT is a live deploy pipeline: watch it (validated
            # auto-publish + rollback); an explicit ckpt_* dir is a
            # one-shot serve
            fcfg = FleetConfig.from_params(config)
            watcher = CheckpointWatcher(
                config.input_model,
                RegistryTarget(server, model=fcfg.tenant),
                config=fcfg, recorder=server._recorder).start()
    else:
        server.registry.publish(Booster(model_file=config.input_model))
    try:
        serve_http(server)
    finally:
        if watcher is not None:
            watcher.stop()
        server.stop()


def _task_route(params: Dict[str, str], config: Config) -> None:
    """Routing front (``serve/router.py``, ``docs/Routing.md``): a
    shared-nothing HTTP router balancing over the replica URLs in
    ``route_backends`` with live health/draining/fingerprint
    awareness, bounded retries + hedging, per-backend circuit
    breakers and per-model admission budgets.  Runs until a
    SIGTERM/SIGINT drains it.  Programmatic deployments attach
    FleetSupervisors instead (``Router.add_model``).

    ``slo_enable=true`` runs the SLO engine next to the router
    (burn-rate evaluation over the standard router objectives,
    ``obs/slo.py``); ``autoscale=true`` additionally runs the
    closed-loop controller — with a static backend table its only
    lever is the admission retune (no supervisor to scale), which is
    exactly the degraded-capacity posture the controller is built
    for.  ``docs/Serving.md`` has the full control-policy table."""
    from .serve.config import (AutoscaleConfig, RouterConfig,
                               SloConfig)
    from .serve.router import Router, parse_backends_spec, route_http

    rcfg = RouterConfig.from_params(config)
    table = parse_backends_spec(rcfg.backends)
    if not table:
        Log.fatal("task=route requires route_backends=<url[,name=url+"
                  "url...]> (static table) — programmatic routers use "
                  "Router.add_model")
    recorder = None
    if config.telemetry_file:
        from .utils import telemetry as _telemetry
        recorder = _telemetry.RunRecorder(
            config.telemetry_file, run_info={"task": "route",
                                             "backend": "none"})
    router = Router(rcfg, recorder=recorder)
    for name, urls in table.items():
        router.add_model(name, urls=urls,
                         replica_model="default" if name == "default"
                         else name)
    slo_engine = None
    scaler = None
    scfg = SloConfig.from_params(config)
    acfg = AutoscaleConfig.from_params(config)
    if scfg.enable or acfg.enable:
        from .obs.slo import SloEngine, router_objectives
        slo_engine = SloEngine(router_objectives(router, scfg),
                               config=scfg, recorder=recorder).start()
    if acfg.enable:
        from .serve.autoscaler import Autoscaler
        scaler = Autoscaler(router=router, slo=slo_engine, config=acfg,
                            recorder=recorder).start()
    try:
        route_http(router)
    finally:
        if scaler is not None:
            scaler.stop()
        if slo_engine is not None:
            slo_engine.stop()
        router.stop()
        if recorder is not None:
            recorder.close()


def _task_continual(params: Dict[str, str], config: Config) -> None:
    """Continual training daemon (``docs/Continual.md``): tail
    ``continual_ingest_dir`` for batch shards, gate each through the
    validation pipeline, extend/refit the model, checkpoint into
    ``checkpoint_dir`` — which a serve-tier watcher (``task=serve``
    pointed at the same root) canary-validates and auto-publishes.
    SIGTERM/SIGINT checkpoint at the next served boundary and drain;
    restart resumes bit-exactly."""
    from . import engine as engine_mod
    from .cont import ContinualTrainer
    from .utils import telemetry as _telemetry

    if not config.checkpoint_dir:
        Log.fatal("task=continual requires checkpoint_dir (the "
                  "checkpoint root doubles as the publish root)")
    if not config.continual_ingest_dir:
        Log.fatal("task=continual requires continual_ingest_dir")
    recorder = None
    if config.telemetry_file:
        recorder = _telemetry.RunRecorder(config.telemetry_file)
    # the guard owns SIGTERM/SIGINT on the MAIN thread and raises the
    # process-wide preempt flag the worker-thread training loops
    # observe (engine.request_preempt)
    guard = engine_mod.install_preempt_guard()
    trainer = ContinualTrainer(params, recorder=recorder)
    try:
        stats = trainer.run()
    finally:
        guard.restore()
        if recorder is not None:
            recorder.close()
    Log.info("continual: exit (%s)", stats.get("status", "?"))


def _task_sweep(params: Dict[str, str], config: Config) -> None:
    """Hyperparameter sweep + k-fold CV as one compiled booster
    battery (``engine.sweep``, ``docs/Sweep.md``): candidates from
    ``sweep_grid`` (x ``sweep_random``) score on ``sweep_folds``-fold
    CV over the ONE shared dataset; the winner's full-data model is
    saved to ``output_model``."""
    from .basic import Dataset
    from .engine import sweep
    from .utils import telemetry as _telemetry

    if not config.data:
        Log.fatal("No training data: set data=<file>")
    if not config.sweep_grid and not config.sweep_random:
        Log.warning("task=sweep without sweep_grid: scoring the base "
                    "params on %d-fold CV only", config.sweep_folds)
    recorder = None
    if config.telemetry_file:
        recorder = _telemetry.RunRecorder(
            config.telemetry_file, run_info={"task": "sweep",
                                             "backend": "none"})
        _telemetry.set_recorder(recorder)
    train_set = Dataset(config.data, params=params)
    try:
        res = sweep(params, train_set,
                    num_boost_round=config.num_iterations)
        if res.best_index < 0:
            Log.fatal("sweep: every candidate failed")
        Log.info("sweep: winner c%d %s=%.6g at iteration %d (%s)",
                 res.best_index, res.metric_name, res.best_score,
                 res.best_iteration,
                 ";".join(f"{k}={v}" for k, v in
                          res.candidates[res.best_index].items())
                 or "base params")
        with open(config.output_model, "w") as f:
            f.write(res.model_text)
        Log.info("Finished sweep; winner saved to %s",
                 config.output_model)
    finally:
        if recorder is not None:
            _telemetry.set_recorder(None)
            recorder.close()


def _task_refit(params: Dict[str, str], config: Config) -> None:
    from .basic import Booster
    from .io.parser import parse_file

    if not config.input_model:
        Log.fatal("No model file: set input_model=<file>")
    if not config.data:
        Log.fatal("No data to refit with: set data=<file>")
    from .io.parser import parse_file_full
    booster = Booster(model_file=config.input_model)
    X, y, _, w, _ = parse_file_full(
        config.data, header=config.header,
        label_column=config.label_column,
        ignore_columns=config.ignore_column,
        weight_column=config.weight_column,
        group_column=config.group_column)
    if y is None:
        Log.fatal("refit requires labels in the data file")
    booster.refit(X, y, weight=w, decay_rate=config.refit_decay_rate)
    booster.save_model(config.output_model)
    Log.info("Finished refit; model saved to %s", config.output_model)


def main(argv: List[str] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("tasks: train | predict | convert_model | refit | serve "
              "| route | continual | sweep")
        return 0
    params = _parse_args(argv)
    config = Config(params)
    task = config.task
    if task == "train":
        _task_train(params, config)
    elif task in ("predict", "prediction", "test"):
        _task_predict(params, config)
    elif task == "convert_model":
        _task_convert_model(params, config)
    elif task in ("refit", "refit_tree"):
        _task_refit(params, config)
    elif task == "serve":
        _task_serve(params, config)
    elif task in ("route", "router"):
        _task_route(params, config)
    elif task in ("continual", "continual_train"):
        _task_continual(params, config)
    elif task == "sweep":
        _task_sweep(params, config)
    else:
        Log.fatal("unknown task %r", task)
    return 0


if __name__ == "__main__":
    sys.exit(main())
