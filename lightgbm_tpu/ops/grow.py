"""Leaf-wise (best-first) tree growth, fully on device.

Reference: ``SerialTreeLearner::Train`` (``src/treelearner/
serial_tree_learner.cpp:157-221``): repeat {find best split per leaf →
split the globally-best leaf → build child histograms with the
histogram-subtraction trick (smaller child from scratch, larger =
parent − smaller, ``:506-511``)} until ``num_leaves-1`` splits or no
positive gain.

TPU-first re-design: leaf membership is a dense ``(N,)`` partition-id
vector instead of index lists (``DataPartition``), the growth loop is a
``lax.fori_loop`` with a static ``num_leaves-1`` trip count (no-gain
iterations are masked no-ops), and per-leaf histograms live in a
``(num_leaves, F, B, 3)`` pool (the ``HistogramPool`` analog) enabling
subtraction.  The output is a flat record-of-splits that the host turns
into a :class:`~lightgbm_tpu.models.tree.Tree`.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .histogram import histogram_pallas, histogram_segsum
from .split import NEG_INF, SplitParams, find_best_split, leaf_output

__all__ = ["GrowParams", "build_tree"]


@dataclasses.dataclass(frozen=True)
class GrowParams:
    split: SplitParams
    num_leaves: int
    max_depth: int = -1
    hist_impl: str = "segsum"  # segsum | pallas
    rows_per_block: int = 1024


def _hist(xt, vals, p: GrowParams):
    if p.hist_impl == "pallas":
        return histogram_pallas(xt, vals, p.split.max_bin, p.rows_per_block)
    return histogram_segsum(xt, vals, p.split.max_bin)


@functools.partial(jax.jit, static_argnames=("params",))
def build_tree(xt: jax.Array, grad: jax.Array, hess: jax.Array,
               sample_mask: jax.Array, feature_mask: jax.Array,
               num_bins: jax.Array, missing_type: jax.Array,
               is_cat: jax.Array, params: GrowParams):
    """Grow one tree.

    xt: (F, N) binned features (transposed layout — contiguous per-feature
    rows for the histogram kernel and O(1) column fetch at split time);
    grad/hess/sample_mask: (N,) f32 (mask carries bagging weights and row
    padding); feature_mask: (F,) bool (feature_fraction);
    num_bins/missing_type: (F,) i32; is_cat: (F,) bool.

    Returns a dict of per-split records (length num_leaves-1), final
    leaf assignment, per-leaf values and the realized leaf count.
    """
    p = params
    L = p.num_leaves
    F, N = xt.shape
    B = p.split.max_bin
    sp = p.split

    def masked_hist(leaf_idx, leaf_id):
        m = sample_mask * (leaf_idx == leaf_id)
        vals = jnp.stack([grad * m, hess * m, m], axis=-1)
        return _hist(xt, vals, p)

    def best_of(hist_leaf, stats, depth):
        b = find_best_split(hist_leaf, stats, num_bins, missing_type,
                            is_cat, feature_mask, sp)
        allowed = (p.max_depth <= 0) | (depth < p.max_depth)
        b["gain"] = jnp.where(allowed, b["gain"], NEG_INF)
        return b

    # ---- init: root ------------------------------------------------
    leaf_idx = jnp.zeros(N, dtype=jnp.int32)
    root_hist = masked_hist(leaf_idx, 0)
    root_stats = jnp.stack([jnp.sum(grad * sample_mask),
                            jnp.sum(hess * sample_mask),
                            jnp.sum(sample_mask)])
    root_best = best_of(root_hist, root_stats, jnp.int32(0))

    state = {
        "leaf_idx": leaf_idx,
        "hist": jnp.zeros((L, F, B, 3), jnp.float32).at[0].set(root_hist),
        "leaf_stats": jnp.zeros((L, 3), jnp.float32).at[0].set(root_stats),
        "leaf_depth": jnp.zeros(L, jnp.int32),
        "best_gain": jnp.full(L, NEG_INF, jnp.float32).at[0].set(
            root_best["gain"].astype(jnp.float32)),
        "best_feature": jnp.zeros(L, jnp.int32).at[0].set(
            root_best["feature"]),
        "best_threshold": jnp.zeros(L, jnp.int32).at[0].set(
            root_best["threshold"]),
        "best_default_left": jnp.zeros(L, bool).at[0].set(
            root_best["default_left"]),
        "best_is_cat": jnp.zeros(L, bool).at[0].set(root_best["is_cat"]),
        "best_left_mask": jnp.zeros((L, B), bool).at[0].set(
            root_best["left_mask"]),
        "best_left_stats": jnp.zeros((L, 3), jnp.float32).at[0].set(
            root_best["left_stats"].astype(jnp.float32)),
        "rec_leaf": jnp.zeros(L - 1, jnp.int32),
        "rec_feature": jnp.zeros(L - 1, jnp.int32),
        "rec_threshold": jnp.zeros(L - 1, jnp.int32),
        "rec_default_left": jnp.zeros(L - 1, bool),
        "rec_is_cat": jnp.zeros(L - 1, bool),
        "rec_gain": jnp.zeros(L - 1, jnp.float32),
        "rec_left_stats": jnp.zeros((L - 1, 3), jnp.float32),
        "rec_right_stats": jnp.zeros((L - 1, 3), jnp.float32),
        "rec_left_mask": jnp.zeros((L - 1, B), bool),
        "rec_valid": jnp.zeros(L - 1, bool),
        "n_leaves": jnp.int32(1),
    }

    def body(t, st):
        l = jnp.argmax(st["best_gain"]).astype(jnp.int32)
        gain = st["best_gain"][l]
        valid = gain > 0

        def do_split(st):
            new = jnp.int32(t + 1)
            feat = st["best_feature"][l]
            col = jax.lax.dynamic_index_in_dim(
                xt, feat, axis=0, keepdims=False)  # (N,)
            goes_left = jnp.take(st["best_left_mask"][l],
                                 col.astype(jnp.int32))
            mine = st["leaf_idx"] == l
            leaf_idx = jnp.where(mine & ~goes_left, new, st["leaf_idx"])

            left_stats = st["best_left_stats"][l]
            parent_stats = st["leaf_stats"][l]
            right_stats = parent_stats - left_stats
            small_is_left = left_stats[2] <= right_stats[2]
            small_id = jnp.where(small_is_left, l, new)
            hist_small = masked_hist(leaf_idx, small_id)
            hist_large = st["hist"][l] - hist_small
            hist_l = jnp.where(small_is_left, hist_small, hist_large)
            hist_r = jnp.where(small_is_left, hist_large, hist_small)

            depth = st["leaf_depth"][l] + 1
            best_l = best_of(hist_l, left_stats, depth)
            best_r = best_of(hist_r, right_stats, depth)

            st = dict(st)
            st["leaf_idx"] = leaf_idx
            st["hist"] = st["hist"].at[l].set(hist_l).at[new].set(hist_r)
            st["leaf_stats"] = st["leaf_stats"].at[l].set(left_stats) \
                                               .at[new].set(right_stats)
            st["leaf_depth"] = st["leaf_depth"].at[l].set(depth) \
                                               .at[new].set(depth)
            for key, src in (("best_gain", "gain"),
                             ("best_feature", "feature"),
                             ("best_threshold", "threshold"),
                             ("best_default_left", "default_left"),
                             ("best_is_cat", "is_cat"),
                             ("best_left_mask", "left_mask"),
                             ("best_left_stats", "left_stats")):
                arr = st[key]
                st[key] = arr.at[l].set(best_l[src].astype(arr.dtype)) \
                             .at[new].set(best_r[src].astype(arr.dtype))
            return st, left_stats, right_stats, gain

        def skip(st):
            return st, jnp.zeros(3, jnp.float32), jnp.zeros(3, jnp.float32), \
                jnp.float32(0)

        # record fields that need pre-split best_* values
        pre = {
            "feature": st["best_feature"][l],
            "threshold": st["best_threshold"][l],
            "default_left": st["best_default_left"][l],
            "is_cat": st["best_is_cat"][l],
            "left_mask": st["best_left_mask"][l],
        }
        st2, ls, rs, g = jax.lax.cond(valid, do_split, skip, st)
        st2["rec_leaf"] = st2["rec_leaf"].at[t].set(
            jnp.where(valid, l, -1))
        st2["rec_feature"] = st2["rec_feature"].at[t].set(pre["feature"])
        st2["rec_threshold"] = st2["rec_threshold"].at[t].set(
            pre["threshold"])
        st2["rec_default_left"] = st2["rec_default_left"].at[t].set(
            pre["default_left"])
        st2["rec_is_cat"] = st2["rec_is_cat"].at[t].set(pre["is_cat"])
        st2["rec_left_mask"] = st2["rec_left_mask"].at[t].set(
            pre["left_mask"])
        st2["rec_gain"] = st2["rec_gain"].at[t].set(g)
        st2["rec_left_stats"] = st2["rec_left_stats"].at[t].set(ls)
        st2["rec_right_stats"] = st2["rec_right_stats"].at[t].set(rs)
        st2["rec_valid"] = st2["rec_valid"].at[t].set(valid)
        st2["n_leaves"] = st2["n_leaves"] + valid.astype(jnp.int32)
        return st2

    state = jax.lax.fori_loop(0, L - 1, body, state)

    leaf_values = leaf_output(state["leaf_stats"][:, 0],
                              state["leaf_stats"][:, 1],
                              sp.lambda_l1, sp.lambda_l2,
                              sp.max_delta_step)
    return {
        "leaf": state["rec_leaf"],
        "feature": state["rec_feature"],
        "threshold": state["rec_threshold"],
        "default_left": state["rec_default_left"],
        "is_cat": state["rec_is_cat"],
        "gain": state["rec_gain"],
        "left_stats": state["rec_left_stats"],
        "right_stats": state["rec_right_stats"],
        "left_mask": state["rec_left_mask"],
        "valid": state["rec_valid"],
        "leaf_idx": state["leaf_idx"],
        "leaf_values": leaf_values,
        "leaf_stats": state["leaf_stats"],
        "n_leaves": state["n_leaves"],
    }
